"""First-class tracing via ``jax.profiler`` (SURVEY.md section 5.1).

The reference has no profiling beyond ad-hoc wall-clock logs of aggregation
(``FedAVGAggregator.py:59,85-86``). On TPU, XLA traces are the primary
performance tool, so round loops here can wrap themselves in
``profile_trace`` (TensorBoard-viewable) and annotate each federated round
as a profiler step.
"""

from __future__ import annotations

import contextlib
import logging


@contextlib.contextmanager
def profile_trace(log_dir, enabled=True):
    """Trace everything inside the block to ``log_dir`` (view in
    TensorBoard's profile plugin). No-op when ``enabled`` is falsy so the
    flag can be wired straight from argparse."""
    if not enabled or log_dir is None:
        yield
        return
    import jax
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logging.info("profiler trace written to %s", log_dir)


def annotate_step(round_idx):
    """Label one federated round as a profiler step:
    ``with annotate_step(r): round_fn(...)``."""
    import jax
    return jax.profiler.StepTraceAnnotation("fed_round", step_num=round_idx)


def end_of_round_sync(state):
    """The round loops' single end-of-round host sync: block until the
    round's outputs are materialized, so ``round_time_s`` measures device
    work instead of dispatch latency. Every algorithm's round loop funnels
    through here rather than calling ``jax.block_until_ready`` ad hoc --
    it is the one interception point the runtime auditor
    (``fedml_tpu.analysis.runtime.audit``) uses to bucket (re)trace counts
    per round and arm the transfer guard, and the compile-event watcher
    (``fedml_tpu.observability.jaxmon``) uses to bucket compile count +
    duration per round. Returns ``state``."""
    from fedml_tpu.analysis.runtime import current_auditor
    from fedml_tpu.observability.jaxmon import current_watcher

    auditor = current_auditor()
    if auditor is not None:
        state = auditor.sync_and_mark_round(state)
    else:
        import jax
        jax.block_until_ready(state)
    watcher = current_watcher()
    if watcher is not None:
        watcher.mark_round()
    return state


@contextlib.contextmanager
def off_round_work():
    """Mark host-driven work that legitimately falls between federated
    rounds (periodic eval, checkpoint restore). No-op normally; under an
    active runtime auditor the work's compile/trace events are booked as
    trailing instead of polluting the next round's retrace bucket."""
    from fedml_tpu.analysis.runtime import current_auditor

    auditor = current_auditor()
    if auditor is None:
        yield
        return
    with auditor.off_round():
        yield
