"""First-class tracing via ``jax.profiler`` (SURVEY.md section 5.1).

The reference has no profiling beyond ad-hoc wall-clock logs of aggregation
(``FedAVGAggregator.py:59,85-86``). On TPU, XLA traces are the primary
performance tool, so round loops here can wrap themselves in
``profile_trace`` (TensorBoard-viewable) and annotate each federated round
as a profiler step.
"""

from __future__ import annotations

import contextlib
import logging


@contextlib.contextmanager
def profile_trace(log_dir, enabled=True):
    """Trace everything inside the block to ``log_dir`` (view in
    TensorBoard's profile plugin). No-op when ``enabled`` is falsy so the
    flag can be wired straight from argparse."""
    if not enabled or log_dir is None:
        yield
        return
    import jax
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logging.info("profiler trace written to %s", log_dir)


def annotate_step(round_idx):
    """Label one federated round as a profiler step:
    ``with annotate_step(r): round_fn(...)``."""
    import jax
    return jax.profiler.StepTraceAnnotation("fed_round", step_num=round_idx)
