"""Checkpoint/resume: orbax over (global params, server state, round, RNG).

The reference's only checkpointer is FedSeg's ``Saver``
(``fedseg/utils.py:169-242``): it writes ``checkpoint.pth.tar`` per
experiment dir, tracks the best metric (best mIoU) across runs in
``best_pred.txt``, and snapshots the config to ``parameters.txt`` -- but
nothing anywhere can *resume*. This module keeps Saver's semantics
(best-metric tracking, config snapshot) and adds real resume: the full
round-loop state -- global model pytree, server optimizer state, round
index, PRNG key -- round-trips through orbax, so a killed run continues
bit-exactly.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

_NO_TEMPLATE = object()  # sentinel: "caller supplied no template"


class Checkpointer:
    """Orbax-backed checkpoint manager with Saver-parity extras."""

    def __init__(self, directory, max_to_keep=3, best_mode: Optional[str] = None,
                 async_save=True):
        """Args:
          directory: checkpoint root (created if absent).
          max_to_keep: retained steps (orbax GC).
          best_mode: None keeps the most recent ``max_to_keep``; "max"/"min"
            keeps the best by the ``metric`` passed to ``save`` (Saver's
            best-mIoU behavior, ``fedseg/utils.py:189-204``).
          async_save: False forces synchronous orbax saves. Required when
            ``save`` can be called from *changing* threads (the resilient
            server snapshots from whichever transport serve thread
            completed the round): orbax's async finalize thread is only
            reset by the thread that started it, so cross-thread async
            saves trip ``assert self._finalize_thread is None``.
        """
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.best_mode = best_mode
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            best_fn=(lambda m: m["metric"]) if best_mode else None,
            best_mode=best_mode or "max",
            enable_async_checkpointing=bool(async_save),
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, round_idx: int, global_state, server_state=(),
             rng=None, metric: Optional[float] = None,
             data_rng=None) -> bool:
        """Checkpoint one round. Returns True if orbax kept it.

        ``data_rng`` is the host-side ``np.random.Generator`` feeding batch
        shuffles; its bit-generator state rides along so resume restores the
        data stream in O(1) with no cohort replay. The resolved packing
        backend (native C++ vs numpy -- different shuffle PRNG families)
        rides too, so restore can detect a backend switch."""
        from fedml_tpu.parallel.packing import packing_backend
        # orbax saves finalize on a background thread and assert that no
        # finalize is still in flight when the next save starts; rounds
        # can turn over faster than a finalize (resilience.RoundRecovery
        # snapshots every round), so drain first
        self._mgr.wait_until_finished()
        payload = {
            "global_state": global_state,
            "server_state": _pack_aux(server_state),
            "rng": rng if rng is not None else jax.random.PRNGKey(0),
            "has_rng": np.asarray(rng is not None),
            "round_idx": np.asarray(round_idx),
            "data_rng_state": _encode_json(
                data_rng.bit_generator.state if data_rng is not None else None),
            "packing_backend": _encode_json(packing_backend()),
        }
        metrics = {"metric": float(metric)} if metric is not None else None
        saved = self._mgr.save(
            round_idx, args=self._ocp.args.StandardSave(payload),
            metrics=metrics)
        if metric is not None:
            self._update_best(round_idx, metric)
        return saved

    def restore(self, round_idx: Optional[int] = None,
                server_state_template=_NO_TEMPLATE) -> Optional[dict]:
        """Restore a round (latest if None). Returns
        ``{"global_state","server_state","rng","round_idx"}`` or None when
        the directory has no checkpoints (fresh start).

        ``server_state_template``: a pytree with the expected server-state
        structure (e.g. the API's freshly-initialized ``server_state``).
        Required when the saved state has a custom pytree structure (optax
        namedtuple states); simple containers (dict/list/tuple/None)
        restore without it. Structure is rebuilt from a JSON description --
        never unpickled -- so a tampered checkpoint directory cannot
        execute code at restore time."""
        self._mgr.wait_until_finished()
        step = round_idx if round_idx is not None else self._mgr.latest_step()
        if step is None:
            return None
        # explicit StandardRestore: a freshly-constructed manager (a
        # restarted process resuming -- the whole point of resume) has no
        # handler registry entry for the saved item and raises KeyError
        # when left to infer it
        payload = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore())
        has_rng = bool(np.asarray(payload.get("has_rng", True)))
        rng_state = _decode_json(payload.get("data_rng_state"))
        data_rng = None
        if rng_state is not None:
            data_rng = np.random.default_rng()
            data_rng.bit_generator.state = rng_state
        from fedml_tpu.parallel.packing import packing_backend
        saved_backend = _decode_json(payload.get("packing_backend"))
        if saved_backend is not None and saved_backend != packing_backend():
            import logging
            logging.warning(
                "checkpoint was written with packing_backend=%s but this "
                "machine resolves %s: batch shuffles will differ after "
                "resume (set FEDML_TPU_PACKING=%s to match)",
                saved_backend, packing_backend(), saved_backend)
        return {
            "global_state": payload["global_state"],
            "server_state": _unpack_aux(payload["server_state"],
                                        server_state_template),
            "rng": (jax.numpy.asarray(payload["rng"], dtype=jax.numpy.uint32)
                    if has_rng else None),
            "round_idx": int(np.asarray(payload["round_idx"])),
            "data_rng": data_rng,
            "packing_backend": saved_backend,
        }

    def latest_round(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def best_round(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.best_step()

    def save_config(self, args) -> None:
        """Config snapshot -- the ``parameters.txt`` of Saver
        (``fedseg/utils.py:206-224``), as JSON (same codec as the
        MetricsLogger's config.json so the two snapshots agree)."""
        from fedml_tpu.utils.metrics import _jsonable
        d = vars(args) if hasattr(args, "__dict__") else dict(args)
        with open(os.path.join(self.directory, "parameters.json"), "w") as f:
            json.dump(_jsonable(d), f, indent=2, sort_keys=True)

    def _update_best(self, round_idx, metric):
        """``best_pred.txt`` tracking across runs (``fedseg/utils.py:189-204``)."""
        path = os.path.join(self.directory, "best_pred.txt")
        best = None
        if os.path.exists(path):
            with open(path) as f:
                best = json.loads(f.read())
        better = (metric < best["metric"] if self.best_mode == "min"
                  else metric > best["metric"]) if best is not None else True
        if better:
            with open(path, "w") as f:
                f.write(json.dumps({"metric": float(metric),
                                    "round": int(round_idx)},
                                   sort_keys=True))

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def _encode_structure(tree):
    """JSON-able structural description of a pytree, leaf slots numbered in
    ``jax.tree.flatten`` order. Custom registered nodes (optax namedtuple
    states etc.) are marked opaque -- they restore only via a caller-supplied
    template. Replaces the earlier pickled-treedef codec: unpickling a
    treedef from a shared checkpoint dir was an arbitrary-code-execution
    hole (round-1 advisor finding)."""
    import itertools

    counter = itertools.count()
    opaque = [False]

    def enc(node):
        if node is None:
            return {"t": "none"}
        if jax.tree_util.all_leaves([node]):
            return {"t": "leaf", "i": next(counter)}
        if isinstance(node, dict) and type(node) is dict:
            keys = sorted(node)  # jax flattens dicts in sorted-key order
            return {"t": "dict", "k": list(keys),
                    "c": [enc(node[k]) for k in keys]}
        if type(node) is list:
            return {"t": "list", "c": [enc(v) for v in node]}
        if type(node) is tuple:
            return {"t": "tuple", "c": [enc(v) for v in node]}
        opaque[0] = True
        return {"t": "opaque", "cls": type(node).__name__}

    return enc(tree), opaque[0]


def _decode_structure(enc, leaves):
    def dec(d):
        t = d["t"]
        if t == "none":
            return None
        if t == "leaf":
            return leaves[d["i"]]
        if t == "dict":
            return {k: dec(c) for k, c in zip(d["k"], d["c"])}
        if t == "list":
            return [dec(c) for c in d["c"]]
        if t == "tuple":
            return tuple(dec(c) for c in d["c"])
        raise ValueError(f"opaque pytree node {d.get('cls')}")
    return dec(enc)


def _pack_aux(tree) -> dict:
    """Orbax needs non-empty array pytrees; arbitrary aux state (possibly an
    empty tuple) rides as numbered leaves + a JSON structure description
    (no pickle anywhere in the checkpoint codec)."""
    leaves, treedef = jax.tree.flatten(tree)
    enc, opaque = _encode_structure(tree)
    return {"leaves": {str(i): leaf for i, leaf in enumerate(leaves)},
            "n": np.asarray(len(leaves)),
            "_structure": _encode_json(
                {"repr": str(treedef), "enc": enc, "opaque": opaque})}


def _unpack_aux(packed, template=_NO_TEMPLATE):
    n = int(np.asarray(packed["n"]))
    leaves = [packed["leaves"][str(i)] for i in range(n)]
    if "_structure" not in packed:
        raise ValueError(
            "checkpoint uses the old pickled-treedef codec; refusing to "
            "unpickle (re-save with this version, or restore leaves "
            "manually)")
    meta = _decode_json(packed["_structure"])
    if template is not _NO_TEMPLATE:
        treedef = jax.tree.structure(template)
        if str(treedef) != meta["repr"]:
            raise ValueError(
                f"server_state_template structure {treedef} does not match "
                f"checkpointed structure {meta['repr']}")
        return jax.tree.unflatten(treedef, leaves)
    if not meta["opaque"]:
        return _decode_structure(meta["enc"], leaves)
    raise ValueError(
        "checkpointed server_state contains custom pytree nodes "
        f"({meta['repr']}); pass server_state_template= to restore()")


def _encode_json(obj) -> np.ndarray:
    """JSON-able object -> uint8 array (orbax leaves must be arrays; RNG
    bit-generator states contain 128-bit ints that need a text codec)."""
    return np.frombuffer(json.dumps(obj, sort_keys=True).encode(),
                         dtype=np.uint8).copy()


def _decode_json(arr):
    if arr is None:
        return None
    return json.loads(np.asarray(arr, dtype=np.uint8).tobytes().decode())


__all__ = ["Checkpointer"]
