"""Observability + persistence utilities (SURVEY.md section 5).

The reference's two metric channels are Python ``logging`` with a
``process_id - timestamp file:line`` format (``main_fedavg.py:285-289``) and
wandb on rank 0 (``main_fedavg.py:297-305``). Its only checkpointer is
FedSeg's ``Saver`` (``fedseg/utils.py:169-242``); tracing is ad-hoc
wall-clock logs. Here these are first-class: a wandb-or-JSONL metrics
logger, orbax checkpoint/resume, and ``jax.profiler`` trace hooks.
"""

from fedml_tpu.utils.logging_utils import init_logging
from fedml_tpu.utils.metrics import MetricsLogger
from fedml_tpu.utils.checkpoint import Checkpointer
from fedml_tpu.utils.profiling import (annotate_step, end_of_round_sync,
                                       off_round_work, profile_trace)

__all__ = ["init_logging", "MetricsLogger", "Checkpointer",
           "profile_trace", "annotate_step", "end_of_round_sync",
           "off_round_work"]
