"""FedNAS: federated architecture search over the DARTS space.

Reference behavior (``fedml_api/distributed/fednas``): each client alternates
an architecture (alpha) update on its validation split with a weight update on
its training split (``FedNASTrainer.py:34-127``, ``architect.step_v2`` at
``:103``); the server does sample-weighted averaging of BOTH weights and alpha
(``FedNASAggregator.py:56-64,95-100``) and records the genotype each round
(``FedNASServerManager.py:58-59``).

TPU-native design: the alternating (arch step, weight step) pair is one scan
step; clients are vmapped; the whole federated search round is one XLA
program. Where the reference approximates the second-order DARTS term with
finite differences (``architect.py:229-260`` Hessian-vector products), JAX
differentiates through the unrolled inner SGD step exactly --
``grad_alpha L_val(w - xi * grad_w L_train(w, alpha), alpha)`` is a single
``jax.grad``.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import client_sampling
from fedml_tpu.utils.profiling import end_of_round_sync
from fedml_tpu.algorithms.specs import make_classification_spec
from fedml_tpu.core import pytree
from fedml_tpu.models.darts import DARTSNetwork, derive_genotype
from fedml_tpu.parallel.packing import pack_cohort, pack_eval


@dataclasses.dataclass(frozen=True)
class FedNASConfig:
    """Search-stage hyperparameters (reference flags ``main_fednas.py:44-99``
    and optimizer construction in ``FedNASTrainer``)."""
    lr: float = 0.025            # weight SGD lr
    momentum: float = 0.9
    weight_decay: float = 3e-4
    grad_clip: float = 5.0       # FedNASTrainer.py:106-113
    arch_lr: float = 3e-4        # Architect Adam
    arch_weight_decay: float = 1e-3
    arch_order: int = 2          # 2 = unrolled (step_v2), 1 = first-order
    unrolled_xi: float = 0.025   # inner-step lr for the unrolled term


def make_search_client_update(spec, cfg: FedNASConfig):
    """Per-client local search: scan of (arch step on val batch, weight step
    on train batch) pairs. ``client_data`` carries parallel train/val batch
    streams (see ``_pack_search_cohort``)."""
    w_opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                        optax.add_decayed_weights(cfg.weight_decay),
                        optax.sgd(cfg.lr, momentum=cfg.momentum))
    a_opt = optax.chain(optax.add_decayed_weights(cfg.arch_weight_decay),
                        optax.adam(cfg.arch_lr, b1=0.5, b2=0.999))

    def _loss(state, batch, rng):
        return spec.loss_fn(state, batch, rng, True)

    def client_update(global_state, client_data, rng):
        arch = global_state["arch"]
        params = global_state["params"]
        rest = {k: v for k, v in global_state.items()
                if k not in ("arch", "params")}
        w_state = w_opt.init(params)
        a_state = a_opt.init(arch)
        S = client_data["mask"].shape[0]

        def step(carry, xs):
            params, arch, rest, w_state, a_state = carry
            (train_batch, val_batch), step_idx = xs
            step_rng = jax.random.fold_in(rng, step_idx)

            # --- architecture step on the validation batch ---
            def val_loss(a):
                if cfg.arch_order == 2:
                    def train_loss(p):
                        st = dict(rest); st["params"] = p; st["arch"] = a
                        return _loss(st, train_batch, step_rng)[0]
                    g = jax.grad(train_loss)(params)
                    p2 = jax.tree.map(lambda p_, g_: p_ - cfg.unrolled_xi * g_,
                                      params, g)
                else:
                    p2 = params
                st = dict(rest); st["params"] = p2; st["arch"] = a
                return _loss(st, val_batch, step_rng)[0]

            a_grads = jax.grad(val_loss)(arch)
            a_updates, new_a_state = a_opt.update(a_grads, a_state, arch)
            new_arch = optax.apply_updates(arch, a_updates)

            # --- weight step on the training batch ---
            def train_loss2(p):
                st = dict(rest); st["params"] = p; st["arch"] = new_arch
                return _loss(st, train_batch, step_rng)

            (loss, (new_state, metrics)), grads = jax.value_and_grad(
                train_loss2, has_aux=True)(params)
            w_updates, new_w_state = w_opt.update(grads, w_state, params)
            new_params = optax.apply_updates(params, w_updates)
            new_rest = {k: new_state[k] for k in rest}

            valid = jnp.sum(train_batch["mask"]) > 0
            new_carry = jax.tree.map(
                lambda a_, b_: jnp.where(valid, a_, b_),
                (new_params, new_arch, new_rest, new_w_state, new_a_state),
                (params, arch, rest, w_state, a_state))
            return new_carry, metrics

        train_batches = {k: client_data[k] for k in ("x", "y", "mask")}
        val_batches = {"x": client_data["val_x"], "y": client_data["val_y"],
                       "mask": jnp.ones(client_data["val_y"].shape[:2],
                                        jnp.float32)}
        (params, arch, rest, _, _), metrics = jax.lax.scan(
            step, (params, arch, rest, w_state, a_state),
            ((train_batches, val_batches), jnp.arange(S)))
        local_state = dict(rest)
        local_state["params"] = params
        local_state["arch"] = arch
        aux = {"n": client_data["n"]}
        return local_state, aux, jax.tree.map(lambda m: jnp.sum(m, axis=0),
                                              metrics)

    return client_update


def _pack_search_cohort(datasets, batch_size, epochs, rng):
    """Split each client's shard 50/50 into train/val (reference FedNAS search
    splits the local set for the bilevel objective), pack the train half with
    mask-and-pad, and cycle the val half into a parallel ``[S, B]`` stream
    (wrap-around sampling -- every val batch is full, so no val mask)."""
    train_sets, val_sets = [], []
    for d in datasets:
        n = len(d["y"])
        split = max(1, n // 2)
        train_sets.append({"x": d["x"][:split], "y": d["y"][:split]})
        val_sets.append({"x": d["x"][split:] if n - split > 0 else d["x"][:split],
                         "y": d["y"][split:] if n - split > 0 else d["y"][:split]})
    packed = pack_cohort(train_sets, batch_size, epochs, rng=rng)
    S, B = packed["mask"].shape[1], packed["mask"].shape[2]
    val_x, val_y = [], []
    for d in val_sets:
        n = len(d["y"])
        if n == 0:
            # empty shard: zero batches are safe -- the client's all-zero train
            # mask gates every carry update and its n=0 zeroes its aggregation
            # weight, matching pack_cohort's empty-client handling
            val_x.append(np.zeros((S, B) + d["x"].shape[1:], d["x"].dtype))
            val_y.append(np.zeros((S, B) + d["y"].shape[1:], d["y"].dtype))
            continue
        idx = np.concatenate([rng.permutation(n)
                              for _ in range(int(np.ceil(S * B / n)) + 1)])[:S * B]
        val_x.append(d["x"][idx].reshape((S, B) + d["x"].shape[1:]))
        val_y.append(d["y"][idx].reshape((S, B) + d["y"].shape[1:]))
    packed["val_x"] = np.stack(val_x)
    packed["val_y"] = np.stack(val_y)
    return packed


class FedNASAPI:
    """Federated DARTS search (stage ``search`` of ``main_fednas.py``).

    ``dataset`` is the 8-tuple contract; the model is the DARTS search
    network. Every round: sample cohort -> vmapped local bilevel search ->
    weighted average of weights AND alphas -> derive genotype.
    """

    def __init__(self, dataset, args, model=None, cfg: FedNASConfig = None,
                 metrics_logger=None):
        (self.train_data_num, self.test_data_num, self.train_data_global,
         self.test_data_global, self.train_data_local_num_dict,
         self.train_data_local_dict, self.test_data_local_dict,
         self.class_num) = dataset
        self.args = args
        self.cfg = cfg or FedNASConfig(
            lr=getattr(args, "lr", 0.025),
            arch_order=getattr(args, "arch_order", 2))
        self.model = model or DARTSNetwork(
            C=getattr(args, "init_channels", 16),
            layers=getattr(args, "layers", 8),
            num_classes=self.class_num)
        example = jnp.zeros((1,) + self.train_data_global["x"].shape[1:],
                            jnp.float32)
        self.spec = make_classification_spec(self.model, example, name="fednas")
        self.metrics_logger = metrics_logger or (lambda d: logging.info("%s", d))

        seed = getattr(args, "seed", 0)
        self.rng = jax.random.PRNGKey(seed)
        self.global_state = self.spec.init_fn(jax.random.fold_in(self.rng, 0))
        self._data_rng = np.random.default_rng(seed)
        self.round_idx = 0
        self.history = []

        client_update = make_search_client_update(self.spec, self.cfg)

        @partial(jax.jit, donate_argnums=(0,))
        def round_fn(global_state, cohort_data, rng):
            C = cohort_data["mask"].shape[0]
            rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
            local_states, aux, metrics = jax.vmap(
                client_update, in_axes=(None, 0, 0))(
                    global_state, cohort_data, rngs)
            new_global = pytree.tree_weighted_mean(local_states, aux["n"])
            return new_global, {"aux": aux, "metrics": metrics}

        self.round_fn = round_fn
        from fedml_tpu.parallel.engine import make_eval_fn
        self.eval_fn = make_eval_fn(self.spec)

    def train_one_round(self):
        t0 = time.time()
        idxs = client_sampling(self.round_idx, len(self.train_data_local_dict),
                               self.args.client_num_per_round)
        datasets = [self.train_data_local_dict[i] for i in idxs]
        packed = _pack_search_cohort(datasets, self.args.batch_size,
                                     self.args.epochs, self._data_rng)
        self.rng, round_rng = jax.random.split(self.rng)
        self.global_state, info = self.round_fn(self.global_state, packed,
                                                round_rng)
        end_of_round_sync(self.global_state)
        m = jax.tree.map(np.asarray, info["metrics"])
        out = {"round": self.round_idx,
               "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
               "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1)),
               "genotype": self.genotype(),
               "round_time_s": time.time() - t0}
        self.metrics_logger({k: v for k, v in out.items() if k != "genotype"})
        self.history.append(out)
        self.round_idx += 1
        return out

    def genotype(self):
        return derive_genotype(jax.tree.map(np.asarray,
                                            self.global_state["arch"]))

    def evaluate(self):
        data = pack_eval(self.test_data_global, self.args.batch_size)
        m = jax.tree.map(np.asarray, self.eval_fn(self.global_state, data))
        return {"Test/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1)),
                "Test/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1))}

    def train(self):
        for _ in range(self.args.comm_round):
            self.train_one_round()
        return self.genotype()
