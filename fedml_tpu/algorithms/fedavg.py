"""FedAvg: the north-star algorithm (reference ``fedml_api/distributed/fedavg``
+ ``fedml_api/standalone/fedavg``).

One API class serves both reference paradigms: ``mesh=None`` runs the
vmapped single-chip simulation (semantics of ``fedavg_api.py:40-115``);
passing a mesh runs the shard_map/psum round (semantics of
``FedAVGAggregator.py:58-87`` + managers, minus the pickle transport).
"""

from __future__ import annotations

import contextlib
import logging
import time

import jax
import numpy as np

from fedml_tpu.core.trainer import TrainSpec
from fedml_tpu.observability.perfmon import get_perf_monitor
from fedml_tpu.observability.tracing import get_tracer
from fedml_tpu.utils.profiling import end_of_round_sync
from fedml_tpu.parallel.engine import (
    ClientUpdateConfig, LaneRunner, ShardedLaneRunner, WaveRunner,
    make_indexed_sim_round, make_eval_fn)
from fedml_tpu.parallel.mesh import shard_cohort  # noqa: F401 (re-export)
from fedml_tpu.parallel.packing import (
    pack_cohort, pack_eval, pack_schedule, stack_clients)
# the cohort-seed fold and the reference's seeded sampling now live in
# the program's cohort leg (the ONE definition shared by the simulation
# path and the distributed FSM -- the cross-path A/B and resume
# contracts depend on them agreeing); re-exported under their historical
# home for the many algorithm/test callers that import them from here
from fedml_tpu.program import RoundProgram
from fedml_tpu.program.cohort import (  # noqa: F401 (re-export)
    attempt_seed, client_sampling)


class FedAvgAPI:
    """Round-loop orchestrator.

    Args:
      dataset: the 8-tuple contract (SURVEY.md section 1 L2):
        [train_data_num, test_data_num, train_data_global, test_data_global,
         train_data_local_num_dict, train_data_local_dict,
         test_data_local_dict, class_num] where local dicts map
        client_idx -> {"x": np.ndarray, "y": np.ndarray}.
      spec: TrainSpec for the model/task.
      args: hyperparameters (client_num_per_round, comm_round, epochs,
        batch_size, lr, client_optimizer, wd, frequency_of_the_test, ci).
      mesh: optional jax Mesh -- enables the sharded round path.
      payload_fn / server_fn / server_state: aggregator hooks for algorithm
        variants (FedOpt, FedNova, robust FedAvg) built on this same loop.
      compressor: client-update compression spec (``"topk:0.01"``,
        ``"qsgd:8"``, ``"signsgd"``, ... -- ``fedml_tpu.compression``) or a
        Compressor instance; defaults to ``args.compressor``. Runs the
        compressed round with per-client error-feedback residuals and logs
        ``bytes_on_wire`` / ``compression_ratio`` per round. Simulation
        path only: on a mesh, aggregation is ICI collectives where the
        wire bottleneck this models does not exist.
    """

    def __init__(self, dataset, spec: TrainSpec, args, mesh=None,
                 payload_fn=None, server_fn=None, server_state=None,
                 metrics_logger=None, compressor=None):
        (self.train_data_num, self.test_data_num, self.train_data_global,
         self.test_data_global, self.train_data_local_num_dict,
         self.train_data_local_dict, self.test_data_local_dict,
         self.class_num) = dataset
        self.spec = spec
        self.args = args
        self.mesh = mesh
        self.metrics_logger = metrics_logger or (lambda d: logging.info("%s", d))

        cfg = ClientUpdateConfig(
            optimizer=getattr(args, "client_optimizer", "sgd"),
            lr=args.lr,
            weight_decay=getattr(args, "wd", 0.0),
            momentum=getattr(args, "momentum", 0.0),
            grad_clip=getattr(args, "grad_clip", None))
        self.cfg = cfg
        from fedml_tpu.compression import get_compressor
        self.compressor = get_compressor(
            compressor if compressor is not None
            else getattr(args, "compressor", None))
        if self.compressor is not None and mesh is not None:
            raise ValueError(
                "compressor= applies to the single-chip simulation and the "
                "distributed control-plane paths; mesh rounds aggregate "
                "over ICI collectives, where the wire bottleneck being "
                "compressed does not exist")
        # Bucketed ragged streaming + optional buffered-async aggregation
        # (--bucket_edges / --async_agg): the massive-cohort path. Clients
        # are bucketed by local step count, streamed chunk-by-chunk
        # through one compiled program per bucket shape, and folded on
        # host in fp64 -- the cohort axis is unbounded (engine.py
        # BucketedStreamRunner; docs/PERFORMANCE.md round 6). Composes
        # with --compressor (streaming-EF: the chunk program compresses
        # each lane's update delta with per-client error feedback).
        # Validated BEFORE any round fn is built: a bogus mesh combo
        # must fail loudly here, not deep in shard_map.
        self.bucket_runner = None
        self.async_agg = None
        from fedml_tpu.program import AggregationPolicy
        async_policy = AggregationPolicy.from_args(args)
        use_buckets = (getattr(args, "bucket_edges", None) is not None
                       or async_policy is not None)
        if use_buckets:
            if mesh is not None:
                raise ValueError(
                    "--bucket_edges/--async_agg run the single-chip "
                    "bucketed streaming path; it does not compose with "
                    "--mesh (the sharded-lane path owns multi-chip)")
            if (self.compressor is not None
                    and self.compressor.name == "none"):
                # the identity compressor has no wire transform to
                # stream: keep the plain chunk program so --compressor
                # none stays bitwise-identical to no flag at all
                logging.info("bucketed streaming: --compressor none is "
                             "the identity -- running the plain chunk "
                             "program (bitwise)")
                self.compressor = None

        # the ONE RoundProgram this API executes: the arg surface's
        # cohort/aggregation/codec legs as pure data, jitted below via
        # compile_sim / compile_bucketed (the distributed control plane
        # drives the same program through its host view -- the
        # conformance suite pins the two consumers equal). Built AFTER
        # the --compressor none bucketed identity resolution so the
        # codec leg matches what actually runs.
        self.program = RoundProgram.from_args(
            args,
            codec=(self.compressor if self.compressor is not None
                   else "none"),
            client_update=(spec, cfg))
        self._host = self.program.host_view()

        self.compressed_round_fn = None
        if mesh is None:
            self.round_fn = self.program.compile_sim(
                spec, cfg, payload_fn, server_fn, compressed=False)
            if self.compressor is not None and not use_buckets:
                # the resolved instance is passed through: CodecSpec
                # coercion would re-derive it from the spec string and
                # drop instance-level configuration
                self.compressed_round_fn = self.program.compile_sim(
                    spec, cfg, payload_fn, server_fn, compressed=True,
                    compressor=self.compressor)
        else:
            self.round_fn = self.program.compile_sim(
                spec, cfg, payload_fn, server_fn, mesh=mesh)
        self.eval_fn = make_eval_fn(spec)

        if use_buckets:
            from fedml_tpu.parallel.packing import (_steps_for,
                                                    parse_bucket_edges)
            # edges are sized from the POPULATION max so bucket shapes --
            # and therefore compiled programs -- are stable across rounds
            # no matter which cohort is sampled
            pop_ns = [int(v)
                      for v in self.train_data_local_num_dict.values()]
            eff_bs = (args.batch_size
                      if args.batch_size not in (-1, 0)
                      else max(1, max(pop_ns)))
            s_max = max(_steps_for(max(n, 1), eff_bs, args.epochs)
                        for n in pop_ns)
            edges = parse_bucket_edges(
                getattr(args, "bucket_edges", None), s_max)
            # pass the RESOLVED batch size: -1 (full-batch) must pin to
            # the population max, not each cohort's, or re-sampled
            # cohorts change the compiled [C, S, B] shape
            self.bucket_runner = self.program.compile_bucketed(
                spec, cfg, payload_fn, server_fn,
                compressor=self.compressor,
                client_chunk=getattr(args, "client_chunk", 8) or 8,
                batch_size=eff_bs, epochs=args.epochs, edges=edges)
            if async_policy is not None:
                self.async_agg = self._host.make_aggregator()
                self._async_window = async_policy.async_window

        # Device-resident data path (single-chip): upload every client's
        # padded shard to HBM once; per-round host work shrinks to an index
        # schedule. Auto-enabled when the stacked arrays fit the cap.
        self.device_data = None
        self.sharded_lane_runner = None
        device_resident = getattr(args, "device_resident", "auto")
        if str(device_resident).lower() in ("0", "false", "none", ""):
            device_resident = False
        chunk = getattr(args, "client_chunk", 8) or 8
        # stacking copies the whole dataset host-side: only do it for the
        # paths that will consume it (single-chip residency, or mesh lanes);
        # compressed rounds thread EF residuals, which only the packed-
        # cohort round function does -- residency is bypassed there
        wants_residency = (mesh is None
                           or int(getattr(args, "wave_mode", 1)) in (2, 3))
        stacked = (self._stack_if_fits(args)
                   if device_resident and wants_residency
                   and self.compressor is None
                   and self.bucket_runner is None else None)
        self.packed_lane_runner = None
        if stacked is not None and mesh is None:
            import jax.numpy as jnp
            self.device_data = {"x": jnp.asarray(stacked["host"]["x"]),
                                "y": jnp.asarray(stacked["host"]["y"])}
            self._client_ns = stacked["n"]
            # execution modes for device-resident rounds (--wave_mode):
            # 3 = MXU-packed lanes (lane axis folded into channels,
            # models/lane_packed.py; falls back to 2 for model families
            # without a packed lowering), 2 = packed lanes (one dispatch,
            # LPT-balanced), 1 = size-sorted waves (default), 0 = flat
            # single program (A/B / debugging)
            self.wave_runner = WaveRunner(
                spec, cfg, payload_fn, server_fn, client_chunk=chunk)
            self.lane_runner = LaneRunner(
                spec, cfg, payload_fn, server_fn, n_lanes=chunk)
            if (int(getattr(args, "wave_mode", 1)) == 3
                    and spec.lane_loss_builder is not None):
                self.packed_lane_runner = LaneRunner(
                    spec, cfg, payload_fn, server_fn, n_lanes=chunk,
                    packed=True)
            self.indexed_round_fn = make_indexed_sim_round(
                spec, cfg, payload_fn, server_fn,
                client_chunk=getattr(args, "client_chunk", None))
        elif (stacked is not None and mesh is not None
                and int(getattr(args, "wave_mode", 1)) in (2, 3)):
            # mesh + lanes: client rows live SHARDED over the mesh's
            # clients axis; each shard runs its residents as packed lanes
            # and aggregation is one psum (ShardedLaneRunner); wave_mode 3
            # additionally folds each shard's lane axis into channels
            # (MXU-shaped lowering) when the model family supports it
            from fedml_tpu.parallel.multihost import global_cohort
            host = stacked["host"]
            placed = global_cohort(mesh, {"x": host["x"], "y": host["y"]})
            self.device_data = {"x": placed["x"], "y": placed["y"]}
            self._client_ns = stacked["n"]
            self.sharded_lane_runner = ShardedLaneRunner(
                spec, cfg, mesh, payload_fn, server_fn, n_lanes=chunk,
                packed=(int(getattr(args, "wave_mode", 1)) == 3
                        and spec.lane_loss_builder is not None))
        self.server_state = server_state if server_state is not None else ()

        # over-selection + simulated deadline misses (--overselect /
        # --straggler_p): cohort restriction IS the renormalized partial
        # aggregate, since the round fns weight by per-client sample counts
        from fedml_tpu.resilience.integration import SimResilience
        self.resilience = SimResilience.from_args(args)
        self._last_res_record = None
        # closed-loop pace steering for the simulation rounds
        # (--pace_steering, resilience/steering.py): adapts the
        # over-selection eps from the previous round's observed loss
        # fraction -- the sim has no wall clock, so the deadline knobs
        # stay put and the decision stream is a pure function of
        # (seed, trace), bitwise-reproducible across runs. None (the
        # default) is exactly today's sampling path.
        from fedml_tpu.resilience.steering import PaceController
        self.pace = PaceController.from_args(args)
        if self.pace is not None and self.resilience is None:
            logging.warning(
                "--pace_steering without --overselect/--straggler_p: the "
                "simulation rounds have no sampling loop to steer; "
                "ignoring the flag")
            self.pace = None

        seed = getattr(args, "seed", 0)
        self.rng = jax.random.PRNGKey(seed)
        self.global_state = spec.init_fn(jax.random.fold_in(self.rng, 0))
        self._data_rng = np.random.default_rng(seed)
        self.round_idx = 0
        self.history = []

        if self.compressor is not None:
            from fedml_tpu.compression import (ResidualStore,
                                               compressed_payload_nbytes,
                                               raw_payload_nbytes)
            # error-feedback residual per client IN TOTAL, carried across
            # rounds (clients keep their own accumulator between the rounds
            # they are sampled into -- DGC/EF-SignSGD semantics). Keyed by
            # STABLE client id, never cohort slot: re-sampled cohorts must
            # not cross-contaminate accumulators (regression-pinned in
            # tests/test_compression.py). Shared by the packed compressed
            # round and the bucketed streaming-EF path: dense device rows
            # when the population fits dense_cap_gb, lazy host spill
            # beyond (the unbounded-population contract)
            self._ef_store = ResidualStore(
                self.global_state["params"],
                num_clients=len(self.train_data_local_dict),
                dense_cap_gb=float(getattr(args, "device_data_cap_gb",
                                           2.0)))
            # on-wire cost per client update: static given the template, so
            # computed once from abstract shapes (nothing runs on device)
            self._payload_bytes = compressed_payload_nbytes(
                self.compressor, self.global_state["params"])
            self._raw_payload_bytes = raw_payload_nbytes(
                self.global_state["params"])

    def _stack_if_fits(self, args):
        """Stack every client's padded shard for HBM residency when the
        result fits ``device_data_cap_gb``. Applies the optional bf16 cast
        (floating x only -- token ids would be corrupted). Returns
        ``{"host": {"x","y"} numpy (cast applied), "n": [C]}`` or None."""
        import jax.numpy as jnp

        C = len(self.train_data_local_dict)
        n_max = max(1, max(len(d["y"])
                           for d in self.train_data_local_dict.values()))
        x0 = np.asarray(self.train_data_local_dict[0]["x"])
        y0 = np.asarray(self.train_data_local_dict[0]["y"])
        ddt = getattr(args, "device_dtype", None)
        cast_bf16 = (ddt in ("bf16", "bfloat16")
                     and np.issubdtype(x0.dtype, np.floating))
        x_itemsize = 2 if cast_bf16 else x0.dtype.itemsize
        row = (int(np.prod(x0.shape[1:], dtype=np.int64)) * x_itemsize
               + int(np.prod(y0.shape[1:], dtype=np.int64) or 1)
               * y0.dtype.itemsize)
        cap = float(getattr(args, "device_data_cap_gb", 2.0)) * 1e9
        if C * n_max * row > cap:
            return None
        stacked = stack_clients(
            [self.train_data_local_dict[i] for i in range(C)])
        xh = (np.asarray(stacked["x"], dtype=jnp.bfloat16) if cast_bf16
              else stacked["x"])
        return {"host": {"x": xh, "y": stacked["y"]}, "n": stacked["n"]}

    def _sample_cohort(self, round_idx):
        """Cohort for one round: plain seeded sampling, or -- with
        resilience enabled -- over-selection trimmed to the reporting
        subset (``fedml_tpu.resilience.SimResilience.sample``)."""
        if self.resilience is None:
            self._last_res_record = None
            with get_tracer().span("cohort-select", round=int(round_idx)):
                return client_sampling(round_idx,
                                       len(self.train_data_local_dict),
                                       self.args.client_num_per_round)
        if self.pace is not None and self._last_res_record is not None:
            # steer BEFORE sampling: the previous round's loss fraction
            # decides this round's over-selection (within bounds); the
            # decision rides this round's record as pace/* fields
            import dataclasses
            prev = self._last_res_record
            # loss is the shortfall vs the aggregation target C (surplus
            # over-selection trimmed by "first C win" must not read as
            # loss, or eps ratchets on its own success)
            target = min(self.args.client_num_per_round,
                         len(self.train_data_local_dict))
            dec = self.pace.decide(
                outcome=("degraded" if prev["res/degraded"]
                         else "complete"),
                selected=target,
                reporting=min(prev["res/reporting"], target))
            self.resilience.policy = dataclasses.replace(
                self.resilience.policy, overselect=dec.overselect)
            # the program IS the round definition: steering evolves its
            # cohort leg in step so program readers see the live eps
            self.program = self.program.replace(
                cohort=dataclasses.replace(self.program.cohort,
                                           overselect=dec.overselect))
            self._host = self.program.host_view()
        # SimResilience.sample opens its own cohort-select span (carrying
        # the per-attempt selected/reporting attrs)
        client_indexes, record = self.resilience.sample(
            round_idx, len(self.train_data_local_dict),
            self.args.client_num_per_round)
        if self.pace is not None:
            record.update(self.pace.record())
        self._last_res_record = record
        return client_indexes

    def _cohort(self, round_idx):
        client_indexes = self._sample_cohort(round_idx)
        logging.info("client_indexes = %s", client_indexes)
        datasets = [self.train_data_local_dict[i] for i in client_indexes]
        if all(len(d["y"]) == 0 for d in datasets):
            raise ValueError(
                f"round {round_idx}: every sampled client has an empty shard")
        # "broadcast" in the sim: packing + placing the cohort's data is
        # the host->device half of what a distributed round sends out
        with get_tracer().span("broadcast", clients=len(client_indexes)):
            packed = pack_cohort(datasets, self.args.batch_size,
                                 self.args.epochs, rng=self._data_rng)
            if self.mesh is not None:
                # multi-host: every process packed the identical cohort
                # (same seeded RNG stream); each contributes local shards
                from fedml_tpu.parallel.multihost import global_cohort
                packed = global_cohort(self.mesh, packed)
        return client_indexes, packed

    def train_one_round(self):
        # span model (docs/OBSERVABILITY.md): the jitted round fn is
        # dispatched asynchronously, so "local-train" measures dispatch
        # (plus any inline host compute) and the device time lands in
        # "aggregate" -- the end-of-round sync is where the host actually
        # waits for the round's outputs (exactly the FL114 lesson)
        tracer = get_tracer()
        mon = get_perf_monitor()  # one global read when monitoring is off
        t0 = time.time()
        with (mon.xprof(self.round_idx) if mon is not None
              else contextlib.nullcontext()):
            with tracer.span("round", round=int(self.round_idx)):
                train_metrics = self._traced_round_body(tracer, t0)
        if mon is not None:
            # true steps are known host-side only on the bucketed path;
            # elsewhere the per-step histogram is skipped rather than
            # forcing a device read the disabled path would not do
            steps = (self._last_bucket_info["bucket"]["true_steps"]
                     if self.bucket_runner is not None else None)
            mon.observe_round(train_metrics["round_time_s"], steps=steps)
        self.round_idx += 1
        return train_metrics

    def _traced_round_body(self, tracer, t0):
        self.rng, round_rng = jax.random.split(self.rng)
        if self.bucket_runner is not None:
            client_indexes = self._sample_cohort(self.round_idx)
            logging.info("bucketed round over %d clients",
                         len(client_indexes))
            datasets = [self.train_data_local_dict[i]
                        for i in client_indexes]
            if all(len(d["y"]) == 0 for d in datasets):
                raise ValueError(f"round {self.round_idx}: every sampled "
                                 f"client has an empty shard")
            with tracer.span("local-train", mode="bucketed",
                             clients=len(client_indexes)):
                (self.global_state, self.server_state,
                 info) = self.bucket_runner.run_round(
                    self.global_state, self.server_state, datasets,
                    round_rng, data_rng=self._data_rng,
                    aggregator=self.async_agg,
                    async_window=getattr(self, "_async_window", 4),
                    client_ids=client_indexes,
                    residual_store=(self._ef_store
                                    if self.compressor is not None
                                    else None))
            self._last_bucket_info = info
            self._last_cohort_size = len(client_indexes)
        elif self.device_data is not None:
            import jax.numpy as jnp
            client_indexes = self._sample_cohort(self.round_idx)
            logging.info("client_indexes = %s", client_indexes)
            ns = [self._client_ns[i] for i in client_indexes]
            if sum(ns) == 0:
                raise ValueError(f"round {self.round_idx}: every sampled "
                                 f"client has an empty shard")
            with tracer.span("broadcast", clients=len(client_indexes)):
                sched = pack_schedule(ns, self.args.batch_size,
                                      self.args.epochs, rng=self._data_rng)
            mode = int(getattr(self.args, "wave_mode", 1))
            if self.sharded_lane_runner is not None:
                with tracer.span("local-train", mode="sharded-lanes"):
                    (self.global_state, self.server_state,
                     info) = self.sharded_lane_runner.run_round(
                        self.global_state, self.server_state,
                        self.device_data, client_indexes, sched, round_rng)
            elif mode in (2, 3):
                runner = (self.packed_lane_runner
                          if mode == 3 and self.packed_lane_runner is not None
                          else self.lane_runner)
                with tracer.span("local-train",
                                 mode="mxu-lanes" if runner is
                                 self.packed_lane_runner else "lanes"):
                    (self.global_state, self.server_state,
                     info) = runner.run_round(
                        self.global_state, self.server_state,
                        self.device_data, client_indexes, sched, round_rng)
            elif mode == 1:
                with tracer.span("local-train", mode="waves"):
                    (self.global_state, self.server_state,
                     info) = self.wave_runner.run_round(
                        self.global_state, self.server_state,
                        self.device_data, client_indexes, sched, round_rng)
            else:
                with tracer.span("local-train", mode="flat"):
                    sel = jnp.asarray(np.asarray(client_indexes, np.int32))
                    dd = {"x": self.device_data["x"][sel],
                          "y": self.device_data["y"][sel]}
                    sched = {k: jnp.asarray(v) for k, v in sched.items()}
                    (self.global_state, self.server_state,
                     info) = self.indexed_round_fn(
                        self.global_state, self.server_state, dd, sched,
                        round_rng)
        elif self.compressed_round_fn is not None:
            client_indexes, packed = self._cohort(self.round_idx)
            with tracer.span("local-train", mode="compressed"):
                # gather/scatter by stable client id (ResidualStore): the
                # round fn sees cohort-ordered rows, the store owns the
                # id-keyed carry across re-sampled cohorts
                cohort_res = self._ef_store.gather(client_indexes)
                (self.global_state, self.server_state, new_res,
                 info) = self.compressed_round_fn(
                    self.global_state, self.server_state, packed, cohort_res,
                    round_rng)
                self._ef_store.scatter(client_indexes, new_res)
            self._last_cohort_size = len(client_indexes)
        else:
            _, packed = self._cohort(self.round_idx)
            with tracer.span("local-train", mode="packed"):
                self.global_state, self.server_state, info = self.round_fn(
                    self.global_state, self.server_state, packed, round_rng)
        with tracer.span("aggregate"):
            end_of_round_sync(self.global_state)
        dt = time.time() - t0
        with tracer.span("report"):
            from fedml_tpu.parallel.multihost import gather_metrics
            m = gather_metrics(info["metrics"])
        self._last_metrics = m  # full summed-metrics pytree for subclasses
        train_metrics = {
            "round": self.round_idx,
            "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
            "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1)),
            "round_time_s": dt,
        }
        if self._last_res_record is not None:
            train_metrics.update(self._last_res_record)
        if self.bucket_runner is not None:
            b = self._last_bucket_info["bucket"]
            train_metrics.update({
                "bucket/clients": b["clients"],
                "bucket/shapes": b["buckets_used"],
                "bucket/chunks": b["chunks"],
                "bucket/executed_steps": b["executed_steps"],
                "bucket/true_steps": b["true_steps"],
                "bucket/waste_frac": b["waste_frac"],
            })
            if "executed_flops" in b:
                # XLA cost-model attribution (armed via set_cost_model /
                # --costmodel): padded waste in FLOPs from the programs
                # actually compiled, per round
                train_metrics.update({
                    "bucket/executed_flops": b["executed_flops"],
                    "bucket/true_flops": b["true_flops"],
                    "bucket/flops_waste_frac": b["flops_waste_frac"],
                })
            # buffer-depth/staleness series ride every round record on
            # async runs (metrics.jsonl observability contract) even when
            # the registry is off
            train_metrics.update(self._last_bucket_info.get("async") or {})
        if self.compressor is not None:
            # client->server update traffic this round (uplink; the
            # downlink model broadcast is uncompressed and identical in
            # both regimes, so the ratio isolates what compression buys)
            # -- the packed compressed round and the bucketed
            # streaming-EF path account identically: per-client encoded
            # bytes are static given the template
            cohort = self._last_cohort_size
            wire = self._payload_bytes * cohort
            raw = self._raw_payload_bytes * cohort
            # set directly on the record (callers read the returned dict);
            # count_wire is the transports' path and would double-report
            train_metrics["bytes_on_wire"] = wire
            train_metrics["compression_ratio"] = round(raw / wire, 3)
        # round_idx advances in train_one_round (after the round span ends)
        return train_metrics

    def _packed_global_eval(self):
        """Global test set packed ONCE (shared by every evaluate_global,
        incl. subclasses). Small packs additionally stay device-resident
        PERMANENTLY -- gated to 25% of ``device_data_cap_gb`` so the
        steady-state HBM reservation is bounded; configs tuned to the full
        cap should lower it or raise the cap. Large packs cache host-side
        (skipping the re-pack, still re-uploading per eval)."""
        if not hasattr(self, "_eval_packed"):
            packed = pack_eval(self.test_data_global, self.args.batch_size)
            nbytes = sum(v.nbytes for v in packed.values())
            cap = 0.25 * float(
                getattr(self.args, "device_data_cap_gb", 2.0)) * 1e9
            if nbytes <= cap:
                import jax.numpy as jnp
                packed = {k: jnp.asarray(v) for k, v in packed.items()}
            self._eval_packed = packed
        return self._eval_packed

    def evaluate_global(self):
        m = jax.tree.map(np.asarray, self.eval_fn(
            self.global_state, self._packed_global_eval()))
        return {"Test/Loss": float(m["loss_sum"] / max(m["count"], 1)),
                "Test/Acc": float(m["correct"] / max(m["count"], 1))}

    def evaluate_local(self, max_clients=None):
        """Per-client eval on local test shards (reference
        ``_local_test_on_all_clients``, ``fedavg_api.py:117-180``; ``--ci``
        short-circuits to one client, ``fedavg_api.py:157-162``)."""
        if getattr(self.args, "ci", 0):
            max_clients = 1
        totals = None
        for i, d in self.test_data_local_dict.items():
            if max_clients is not None and i >= max_clients:
                break
            if d is None or len(d["y"]) == 0:
                continue
            packed = pack_eval(d, self.args.batch_size)
            m = jax.tree.map(np.asarray, self.eval_fn(self.global_state, packed))
            totals = m if totals is None else jax.tree.map(np.add, totals, m)
        if totals is None:
            return {}
        return {"Test/Loss": float(totals["loss_sum"] / max(totals["count"], 1)),
                "Test/Acc": float(totals["correct"] / max(totals["count"], 1))}

    def train(self, on_round=None):
        """Full training loop (reference ``fedavg_api.py:40-81``): per-round
        cohort sampling, local training, aggregation; eval every
        ``frequency_of_the_test`` rounds and on the final round. Starts at
        ``self.round_idx`` so a checkpoint-restored API resumes mid-run.

        ``on_round(api, metrics)`` is called after each round -- the
        checkpoint/extra-eval hook used by the experiment mains. Each round
        is annotated as a ``jax.profiler`` step so traces segment cleanly.
        """
        from fedml_tpu.utils.profiling import annotate_step, off_round_work

        freq = getattr(self.args, "frequency_of_the_test", 5)
        while self.round_idx < self.args.comm_round:
            with annotate_step(self.round_idx):
                metrics = self.train_one_round()
            last = self.round_idx == self.args.comm_round
            if self.round_idx % freq == 0 or last:
                # eval runs between round syncs: book its (first-time)
                # compile as off-round so the auditor never charges it to
                # the next round's retrace bucket. The span carries the
                # TRAINED round (round_idx already advanced) so it joins
                # the same round as the metrics record it lands in.
                with get_tracer().span(
                        "eval", round=int(metrics.get("round",
                                                      self.round_idx - 1))):
                    with off_round_work():
                        metrics.update(self.evaluate_global())
            self.metrics_logger(metrics)
            self.history.append(metrics)
            if on_round is not None:
                on_round(self, metrics)
        return self.global_state
