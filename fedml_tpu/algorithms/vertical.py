"""Classical vertical (feature-partitioned) FL.

Reference protocol (``fedml_api/distributed/classical_vertical_fl/
guest_trainer.py:59-80`` + ``fedml_api/standalone/classical_vertical_fl/
vfl.py:21-56``): the label-holding *guest* and feature-only *hosts* each run a
local feature extractor producing logit contributions; hosts send theirs to
the guest, the guest sums, computes the loss, and broadcasts the common
gradient w.r.t. the summed logits; each party backprops locally.

TPU re-design: the exchanged quantities (host logits forward, d loss/d logits
backward) are exactly the values JAX's chain rule routes across the party
seam, so the whole protocol is one jitted step over the party list; party
separation is preserved in the pytree structure ``{party_id: params}`` (on a
mesh, parties map to shards of the ``model`` axis and the logit-sum is a
psum). Labels and loss never leave the guest subtree, matching the privacy
boundary of the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.parallel.engine import ClientUpdateConfig, make_optimizer


class VerticalFLAPI:
    """Args:
      party_models: list of flax modules, one per party; index 0 = guest.
      party_data: list of feature matrices ``x_k [n, d_k]`` (same row order --
        the record linkage is assumed done, as in the reference loaders).
      labels: ``y [n]`` binary or ``[n, 1]`` -- held by the guest only.
    """

    def __init__(self, party_models, party_data, labels, args,
                 test_party_data=None, test_labels=None):
        assert len(party_models) == len(party_data)
        self.models = party_models
        self.args = args
        self.n_parties = len(party_models)
        self.x_parts = [np.asarray(x, np.float32) for x in party_data]
        self.y = np.asarray(labels, np.float32).reshape(-1)
        self.x_test = ([np.asarray(x, np.float32) for x in test_party_data]
                       if test_party_data is not None else None)
        self.y_test = (np.asarray(test_labels, np.float32).reshape(-1)
                       if test_labels is not None else None)

        tx = make_optimizer(ClientUpdateConfig(
            optimizer=getattr(args, "client_optimizer", "sgd"),
            lr=args.lr, weight_decay=getattr(args, "wd", 0.0)))
        self.tx = tx
        rng = jax.random.PRNGKey(getattr(args, "seed", 0))
        self.params = [
            m.init(jax.random.fold_in(rng, i), jnp.asarray(x[:1]))
            for i, (m, x) in enumerate(zip(party_models, self.x_parts))]
        self.opts = [tx.init(p) for p in self.params]
        self._data_rng = np.random.default_rng(getattr(args, "seed", 0))
        models = party_models

        def loss_fn(params_list, xs, y):
            # each party contributes a scalar logit per row; guest sums
            contribs = [models[k].apply(params_list[k], xs[k]).reshape(-1)
                        for k in range(len(models))]
            logit = sum(contribs)
            # guest-side binary CE with logits (reference uses BCE on the
            # summed logit, vfl.py:38-44)
            loss = jnp.mean(
                jnp.maximum(logit, 0) - logit * y +
                jnp.log1p(jnp.exp(-jnp.abs(logit))))
            correct = jnp.sum(((logit > 0) == (y > 0.5)))
            return loss, correct

        @jax.jit
        def train_step(params_list, opt_list, xs, y):
            (loss, correct), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params_list, xs, y)
            new_params, new_opts = [], []
            # static-length Python lists (one entry per party), not traced
            # arrays: unrolling K parties is the intent here
            for p, o, g in zip(params_list, opt_list, grads):  # fedlint: disable=FL102
                up, o2 = tx.update(g, o, p)
                new_params.append(optax.apply_updates(p, up))
                new_opts.append(o2)
            return new_params, new_opts, loss, correct

        self._train_step = train_step
        self._loss_fn = jax.jit(loss_fn)
        self.history = []

    def fit(self):
        """Epoch loop over joined minibatches (reference
        ``vfl_fixture.py`` fit loop)."""
        n = len(self.y)
        bs = self.args.batch_size
        for epoch in range(self.args.epochs):
            order = self._data_rng.permutation(n)
            losses, corrects = [], 0.0
            for s in range(0, n, bs):
                idx = order[s:s + bs]
                xs = [jnp.asarray(x[idx]) for x in self.x_parts]
                yb = jnp.asarray(self.y[idx])
                self.params, self.opts, loss, correct = self._train_step(
                    self.params, self.opts, xs, yb)
                losses.append(float(loss))
                corrects += float(correct)
            rec = {"epoch": epoch, "Train/Loss": float(np.mean(losses)),
                   "Train/Acc": corrects / n}
            if self.x_test is not None:
                rec.update(self.evaluate())
            self.history.append(rec)
        return self.history

    def evaluate(self):
        xs = [jnp.asarray(x) for x in self.x_test]
        loss, correct = self._loss_fn(self.params, xs, jnp.asarray(self.y_test))
        return {"Test/Loss": float(loss),
                "Test/Acc": float(correct) / len(self.y_test)}
