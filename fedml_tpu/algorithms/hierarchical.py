"""Hierarchical FL: client -> group -> global two-tier averaging (reference
``fedml_api/standalone/hierarchical_fl/{trainer,group}.py`` -- note the
reference's trainer has a broken import, SURVEY.md "Known reference defects";
the behavior is reconstructed from ``group.py:24-46``: each group runs
``group_comm_round`` FedAvg rounds locally, then groups' models are averaged
globally, weighted by group sample counts).

TPU mapping (SURVEY.md section 2.7): groups are the outer vmap axis, clients
the inner one -- one jitted call per global round executes every group's full
sub-round schedule; on a pod this nests as two mesh axes (ICI within a slice
for the group tier, DCN across for the global tier).
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, client_sampling
from fedml_tpu.core import pytree
from fedml_tpu.parallel.engine import ClientUpdateConfig, make_client_update
from fedml_tpu.parallel.packing import pack_cohort
from fedml_tpu.utils.profiling import end_of_round_sync


class HierarchicalFedAvgAPI(FedAvgAPI):
    """Extra args: ``group_num``, ``group_comm_round`` (reference
    ``main_hierarchical_fl.py`` flags). Clients are assigned to groups
    round-robin; each global round runs ``group_comm_round`` intra-group
    FedAvg rounds inside one jitted program."""

    def __init__(self, dataset, spec, args, mesh=None, metrics_logger=None):
        super().__init__(dataset, spec, args, mesh=mesh,
                         metrics_logger=metrics_logger)
        self.group_num = getattr(args, "group_num", 2)
        self.group_comm_round = getattr(args, "group_comm_round", 1)
        client_update = make_client_update(spec, self.cfg)

        def group_round(group_state, group_data, rng):
            """One intra-group FedAvg round: vmap clients, weighted mean."""
            C = group_data["mask"].shape[0]
            rngs = jax.random.split(rng, C)
            local_states, aux, metrics = jax.vmap(
                client_update, in_axes=(None, 0, 0))(group_state, group_data, rngs)
            return pytree.tree_weighted_mean(local_states, aux["n"]), aux, metrics

        def global_round(global_state, cohort_data, rng):
            """All groups run their sub-rounds from the same global model,
            then group models average weighted by group sample counts.
            cohort_data leading axes: [G, C_per_group, S, B, ...]."""
            G = cohort_data["mask"].shape[0]

            def one_group(group_data, grng):
                def body(state, r):
                    new_state, aux, metrics = group_round(
                        state, group_data, jax.random.fold_in(grng, r))
                    return new_state, (aux, metrics)

                state, (aux, metrics) = jax.lax.scan(
                    body, global_state, jnp.arange(self.group_comm_round))
                n_group = jnp.sum(aux["n"][0])  # n constant across sub-rounds
                return state, n_group, metrics

            grngs = jax.random.split(rng, G)
            group_states, group_ns, metrics = jax.vmap(one_group)(
                cohort_data, grngs)
            new_global = pytree.tree_weighted_mean(group_states, group_ns)
            return new_global, metrics

        self._global_round = jax.jit(global_round, donate_argnums=(0,))

    def train_one_round(self):
        t0 = time.time()
        client_indexes = client_sampling(
            self.round_idx, len(self.train_data_local_dict),
            self.args.client_num_per_round)
        # round-robin group assignment (reference partitions the cohort into
        # group_num groups); unequal groups are padded with empty client slots
        # (weight 0, fully masked) so no sampled client is dropped. The rule
        # is shared with the distributed fan-in tier (net/fanin.py), so the
        # vmapped group axis and the edge-aggregator tree slice identically.
        from fedml_tpu.net.fanin import round_robin_groups
        groups = round_robin_groups(client_indexes, self.group_num)
        per_group = max(len(g) for g in groups)
        logging.info("hierarchical groups = %s", groups)

        empty = {"x": np.zeros((0,) + self.train_data_local_dict[
            client_indexes[0]]["x"].shape[1:],
            self.train_data_local_dict[client_indexes[0]]["x"].dtype),
            "y": np.zeros((0,), self.train_data_local_dict[
                client_indexes[0]]["y"].dtype)}
        packs = [pack_cohort(
            [self.train_data_local_dict[i] for i in g] +
            [empty] * (per_group - len(g)),
            self.args.batch_size, self.args.epochs, rng=self._data_rng)
            for g in groups]
        S = max(p["mask"].shape[1] for p in packs)
        for p in packs:
            pad = S - p["mask"].shape[1]
            if pad:
                for k in ("x", "y", "mask"):
                    p[k] = np.concatenate(
                        [p[k], np.zeros((p[k].shape[0], pad) + p[k].shape[2:],
                                        p[k].dtype)], axis=1)
        cohort = {k: np.stack([p[k] for p in packs]) for k in packs[0]}

        self.rng, round_rng = jax.random.split(self.rng)
        self.global_state, metrics = self._global_round(
            self.global_state, cohort, round_rng)
        end_of_round_sync(self.global_state)
        m = jax.tree.map(np.asarray, metrics)
        out = {
            "round": self.round_idx,
            "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
            "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1)),
            "round_time_s": time.time() - t0,
        }
        self.round_idx += 1
        return out
