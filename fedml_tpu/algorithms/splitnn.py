"""SplitNN: split learning with per-batch activation/gradient exchange
(reference ``fedml_api/distributed/split_nn/``: client half forwards a batch,
sends activations+labels; the server half computes loss, backprops, and
returns the activation gradient; clients proceed in a relay ring --
``client_manager.py:35-70``, ``server.py:40-60``).

TPU re-design: the activation handoff is a *program seam*, not a network hop.
One jitted step computes client-half forward, server-half forward/backward,
and the client-half backward via the chain rule -- what crossed the process
boundary twice per minibatch (the reference's latency-critical path,
SURVEY.md section 3.3) becomes a fused XLA program. The relay-ring semantics
(clients train sequentially against an evolving server half) are preserved by
scanning clients in ring order within the round. On a multi-host mesh the
seam maps to a mesh partition with activation transfer over ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.parallel.engine import ClientUpdateConfig, make_optimizer
from fedml_tpu.parallel.packing import pack_cohort, pack_eval


class SplitNNAPI:
    """Args: dataset 8-tuple, ``client_model`` / ``server_model`` flax modules
    where ``client_model.apply -> activations`` and ``server_model.apply ->
    logits``. The client half is personal (per-client params); the server
    half is shared and updated continuously in ring order."""

    def __init__(self, dataset, client_model, server_model, args,
                 metrics_logger=None):
        (_, _, _, self.test_data_global, _, self.train_data_local_dict,
         self.test_data_local_dict, self.class_num) = dataset
        self.args = args
        self.client_model = client_model
        self.server_model = server_model
        self.metrics_logger = metrics_logger or (lambda d: None)
        self.n_clients = len(self.train_data_local_dict)

        cfg = ClientUpdateConfig(
            optimizer=getattr(args, "client_optimizer", "sgd"),
            lr=args.lr, weight_decay=getattr(args, "wd", 0.0),
            momentum=getattr(args, "momentum", 0.0))
        self.tx = make_optimizer(cfg)

        rng = jax.random.PRNGKey(getattr(args, "seed", 0))
        example = jnp.asarray(self.train_data_local_dict[0]["x"][:1])
        self.client_params = jax.vmap(
            lambda k: client_model.init(k, example)
        )(jax.random.split(jax.random.fold_in(rng, 1), self.n_clients))
        acts = client_model.apply(
            jax.tree.map(lambda x: x[0], self.client_params), example)
        self.server_params = server_model.init(jax.random.fold_in(rng, 2), acts)
        self.client_opt = jax.vmap(self.tx.init)(self.client_params)
        self.server_opt = self.tx.init(self.server_params)
        self.rng = rng
        self._data_rng = np.random.default_rng(getattr(args, "seed", 0))
        self.round_idx = 0

        def loss_fn(cp, sp, batch):
            acts = client_model.apply(cp, batch["x"])
            logits = server_model.apply(sp, acts)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(
                logp, batch["y"][:, None].astype(jnp.int32), axis=1)[:, 0]
            mask = batch["mask"]
            count = jnp.maximum(jnp.sum(mask), 1.0)
            loss = jnp.sum(-ll * mask) / count
            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == batch["y"]) * mask)
            return loss, {"loss_sum": jnp.sum(-ll * mask), "correct": correct,
                          "count": jnp.sum(mask)}

        def train_client(carry, client_idx, cohort):
            sp, s_opt, cps, c_opts = carry
            cp = jax.tree.map(lambda x: x[client_idx], cps)
            c_opt = jax.tree.map(lambda x: x[client_idx], c_opts)
            data = jax.tree.map(lambda x: x[client_idx], cohort)

            def batch_step(inner, xs):
                cp, c_opt, sp, s_opt = inner
                batch = xs
                (loss, metrics), grads = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True)(cp, sp, batch)
                g_c, g_s = grads
                valid = jnp.sum(batch["mask"]) > 0
                up_c, c_opt2 = self.tx.update(g_c, c_opt, cp)
                up_s, s_opt2 = self.tx.update(g_s, s_opt, sp)
                new = (optax.apply_updates(cp, up_c), c_opt2,
                       optax.apply_updates(sp, up_s), s_opt2)
                out = jax.tree.map(
                    lambda a, b: jnp.where(valid, a, b), new,
                    (cp, c_opt, sp, s_opt))
                return out, metrics

            batches = {k: data[k] for k in ("x", "y", "mask")}
            (cp, c_opt, sp, s_opt), metrics = jax.lax.scan(
                batch_step, (cp, c_opt, sp, s_opt), batches)
            cps = jax.tree.map(
                lambda all_, one: all_.at[client_idx].set(one), cps, cp)
            c_opts = jax.tree.map(
                lambda all_, one: all_.at[client_idx].set(one), c_opts, c_opt)
            msum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
            return (sp, s_opt, cps, c_opts), msum

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def round_fn(sp, s_opt, cps, c_opts, cohort, rng):
            def body(carry, idx):
                return train_client(carry, idx, cohort)

            (sp, s_opt, cps, c_opts), metrics = jax.lax.scan(
                body, (sp, s_opt, cps, c_opts),
                jnp.arange(self.n_clients))  # ring order
            return sp, s_opt, cps, c_opts, metrics

        self._round_fn = round_fn

    def train_one_round(self):
        packed = pack_cohort(
            [self.train_data_local_dict[i] for i in range(self.n_clients)],
            self.args.batch_size, self.args.epochs, rng=self._data_rng)
        self.rng, rng = jax.random.split(self.rng)
        (self.server_params, self.server_opt, self.client_params,
         self.client_opt, metrics) = self._round_fn(
            self.server_params, self.server_opt, self.client_params,
            self.client_opt, packed, rng)
        m = jax.tree.map(np.asarray, metrics)
        out = {"round": self.round_idx,
               "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
               "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1))}
        self.round_idx += 1
        self.metrics_logger(out)
        return out

    def evaluate(self, client_idx=0):
        """Eval through client ``client_idx``'s half + the shared server half
        (reference ``run_eval``, ``client_manager.py:40-55``)."""
        packed = pack_eval(self.test_data_global, self.args.batch_size)
        cp = jax.tree.map(lambda x: x[client_idx], self.client_params)

        def step(carry, batch):
            acts = self.client_model.apply(cp, batch["x"])
            logits = self.server_model.apply(self.server_params, acts)
            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == batch["y"]) * batch["mask"])
            return carry, {"correct": correct, "count": jnp.sum(batch["mask"])}

        _, m = jax.lax.scan(step, 0,
                            {k: jnp.asarray(packed[k]) for k in ("x", "y", "mask")})
        m = jax.tree.map(lambda x: float(np.asarray(x).sum()), m)
        return {"Test/Acc": m["correct"] / max(m["count"], 1)}

    def train(self):
        for _ in range(self.args.comm_round):
            out = self.train_one_round()
        return out
