"""Centralized (non-FL) baseline trainer over the pooled dataset.

Reference: ``fedml_api/centralized/centralized_trainer.py:9-60`` -- the
baseline used by the CI equivalence checks: with full batch and one local
epoch, FedAvg over all clients must match centralized training to 3
decimals (``CI-script-fedavg.sh:42-47``). Implemented as a single "client"
running the same jitted local-update program as FedAvg, so the equivalence
is an algebraic identity of the shared engine, not a coincidence.
"""

from __future__ import annotations

import logging
import time

import jax
import numpy as np

from fedml_tpu.core.trainer import TrainSpec
from fedml_tpu.parallel.engine import (
    ClientUpdateConfig, make_client_update, make_eval_fn)
from fedml_tpu.parallel.packing import pack_cohort, pack_eval
from fedml_tpu.utils.profiling import end_of_round_sync


class CentralizedTrainer:
    """Epoch-loop trainer on the pooled (global) dataset.

    Args mirror the FL APIs; ``epochs`` acts per ``train()`` call and
    ``comm_round`` is the number of such calls so run lengths are directly
    comparable to federated runs.
    """

    def __init__(self, dataset, spec: TrainSpec, args, metrics_logger=None):
        (self.train_data_num, self.test_data_num, self.train_data_global,
         self.test_data_global, _, _, _, self.class_num) = dataset
        self.spec = spec
        self.args = args
        self.metrics_logger = metrics_logger or (lambda d: logging.info("%s", d))
        cfg = ClientUpdateConfig(
            optimizer=getattr(args, "client_optimizer", "sgd"),
            lr=args.lr,
            weight_decay=getattr(args, "wd", 0.0),
            momentum=getattr(args, "momentum", 0.0))
        self._update = jax.jit(make_client_update(spec, cfg))
        self.eval_fn = make_eval_fn(spec)

        seed = getattr(args, "seed", 0)
        self.rng = jax.random.PRNGKey(seed)
        self.global_state = spec.init_fn(jax.random.fold_in(self.rng, 0))
        self._data_rng = np.random.default_rng(seed)
        self.round_idx = 0
        self.history = []

    def train_one_round(self):
        """One "round" = ``args.epochs`` epochs over the pooled data through
        the same client-update program FedAvg uses."""
        t0 = time.time()
        packed = pack_cohort([self.train_data_global], self.args.batch_size,
                             self.args.epochs, rng=self._data_rng)
        one = jax.tree.map(lambda a: a[0], packed)
        self.rng, rng = jax.random.split(self.rng)
        new_state, _, metrics = self._update(self.global_state, one, rng)
        end_of_round_sync(new_state)
        self.global_state = new_state
        m = jax.tree.map(np.asarray, metrics)
        out = {"round": self.round_idx,
               "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
               "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1)),
               "round_time_s": time.time() - t0}
        self.round_idx += 1
        return out

    def evaluate_global(self):
        packed = pack_eval(self.test_data_global, self.args.batch_size)
        m = jax.tree.map(np.asarray, self.eval_fn(self.global_state, packed))
        return {"Test/Loss": float(m["loss_sum"] / max(m["count"], 1)),
                "Test/Acc": float(m["correct"] / max(m["count"], 1))}

    def train(self, on_round=None):
        from fedml_tpu.utils.profiling import off_round_work

        freq = getattr(self.args, "frequency_of_the_test", 5)
        while self.round_idx < self.args.comm_round:
            metrics = self.train_one_round()
            last = self.round_idx == self.args.comm_round
            if self.round_idx % freq == 0 or last:
                # see FedAvgAPI.train: eval compiles are off-round work
                with off_round_work():
                    metrics.update(self.evaluate_global())
            self.metrics_logger(metrics)
            self.history.append(metrics)
            if on_round is not None:
                on_round(self, metrics)
        return self.global_state
