"""TrainSpec builders: wrap a Flax model into the pure-function trainer triple.

These are the TPU equivalents of the reference's task-specific ModelTrainers
(``my_model_trainer_classification.py`` / ``..._nwp.py`` / selected per
dataset at ``fedml_experiments/standalone/fedavg/main_fedavg.py:269-275``):
the loss/metric conventions match so accuracy curves are comparable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.core.trainer import TrainSpec


def _apply_model(model, state, x, rng, train, with_sown=False):
    """Apply with train-time collection handling.

    ``with_sown=True`` (the loss_fn path in every spec) also collects
    losses the model sows (the MoE load-balancing aux, ``models/moe.py``)
    and returns ``(out, new_state, aux_scalar)``; aux is 0.0 for models
    that sow nothing, so non-MoE behavior is unchanged. ``with_sown=
    False`` (eval/metrics path) returns ``(out, new_state)`` -- sow is a
    no-op when the collection is not mutable."""
    variables = dict(state)
    rngs = ({"dropout": rng, "droppath": jax.random.fold_in(rng, 7)}
            if (train and rng is not None) else None)
    mutable = ((["losses"] if with_sown else [])
               + (["batch_stats"]
                  if ("batch_stats" in state and train) else []))
    if not mutable:
        out = model.apply(variables, x, train=train, rngs=rngs)
        return out, state
    out, mutated = model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
    new_state = state
    if "batch_stats" in mutated:
        new_state = dict(state)
        new_state["batch_stats"] = mutated["batch_stats"]
    if not with_sown:
        return out, new_state
    aux = sum(jax.tree.leaves(mutated.get("losses", {})), 0.0)
    return out, new_state, aux


def _init_state(model, example_x, rng):
    """Shared spec init: sown diagnostics (e.g. the MoE aux loss) are
    per-apply values, not model state -- they must not enter the
    aggregated pytree."""
    variables = dict(model.init(rng, example_x, train=False))
    variables.pop("losses", None)
    return variables


def make_classification_spec(model, example_x, num_classes=None,
                             name="classification", augment_fn=None,
                             aux_loss_weight=0.01, lane_lowering=None):
    """Softmax cross-entropy classification over ``[B, C]`` logits.

    Applying log_softmax to whatever the model emits reproduces the reference
    LR quirk automatically (sigmoid output fed to torch CrossEntropyLoss,
    ``lr.py:10-11``). Metrics are *sums* (loss-weighted, correct, count);
    divide on host -- matching the reference's test accumulation
    (``my_model_trainer_classification.py`` test loop).

    ``augment_fn(x, rng)``: optional on-device train-time augmentation
    (``fedml_tpu.data.augment``), applied per step inside client updates.
    """

    def init_fn(rng):
        return _init_state(model, example_x, rng)

    def _loss_and_metrics(logits, y, mask):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ll = jnp.take_along_axis(logp, y[:, None].astype(jnp.int32), axis=1)[:, 0]
        per_sample = -ll
        count = jnp.sum(mask)
        loss = jnp.sum(per_sample * mask) / jnp.maximum(count, 1.0)
        correct = jnp.sum((jnp.argmax(logits, axis=-1) == y) * mask)
        metrics = {"loss_sum": jnp.sum(per_sample * mask),
                   "correct": correct, "count": count}
        return loss, metrics

    def loss_fn(state, batch, rng, train):
        logits, new_state, aux = _apply_model(model, state, batch["x"],
                                              rng, train, with_sown=True)
        loss, metrics = _loss_and_metrics(logits, batch["y"], batch["mask"])
        return loss + aux_loss_weight * aux, (new_state, metrics)

    def metrics_fn(state, batch):
        logits, _ = _apply_model(model, state, batch["x"], None, False)
        _, metrics = _loss_and_metrics(logits, batch["y"], batch["mask"])
        return metrics

    # MXU-shaped packed-lane path (wave_mode=3): the lane_packed registry
    # owns which model families have a packed lowering (None otherwise --
    # runners fall back to the vmap lane path); this module stays
    # model-agnostic
    from fedml_tpu.models.lane_packed import builder_for

    if lane_lowering not in (None, "blockdiag", "bgc", "auto", "pallas"):
        # fail at the API boundary, not hours later at lane setup
        raise ValueError(f"unknown lane_lowering {lane_lowering!r}; "
                         "choose blockdiag, bgc, auto or pallas")
    return TrainSpec(init_fn=init_fn, loss_fn=loss_fn, metrics_fn=metrics_fn,
                     name=name, augment_fn=augment_fn,
                     lane_loss_builder=builder_for(
                         model, lowering=lane_lowering))


def make_seq_classification_spec(model, example_x, ignore_index=0,
                                 name="nwp", aux_loss_weight=0.01):
    """Per-token cross-entropy over ``[B, T, V]`` logits with padding-id
    masking -- semantics of the reference NWP trainer
    (``my_model_trainer_nwp.py:24``: ``CrossEntropyLoss(ignore_index=0)``).
    Token mask = sample mask x (y != ignore_index).

    Losses the model sows (the MoE load-balancing aux,
    ``models/moe.py``) are added at ``aux_loss_weight`` during training
    -- federated MoE trains with balanced routing out of the box.
    """

    def init_fn(rng):
        return _init_state(model, example_x, rng)

    def _loss_and_metrics(logits, y, mask):
        tok_mask = (y != ignore_index).astype(jnp.float32) * mask[:, None]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ll = jnp.take_along_axis(logp, y[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
        count = jnp.sum(tok_mask)
        loss = jnp.sum(-ll * tok_mask) / jnp.maximum(count, 1.0)
        correct = jnp.sum((jnp.argmax(logits, axis=-1) == y) * tok_mask)
        return loss, {"loss_sum": jnp.sum(-ll * tok_mask),
                      "correct": correct, "count": count}

    def loss_fn(state, batch, rng, train):
        logits, new_state, aux = _apply_model(model, state, batch["x"],
                                              rng, train, with_sown=True)
        loss, metrics = _loss_and_metrics(logits, batch["y"], batch["mask"])
        return loss + aux_loss_weight * aux, (new_state, metrics)

    def metrics_fn(state, batch):
        logits, _ = _apply_model(model, state, batch["x"], None, False)
        _, metrics = _loss_and_metrics(logits, batch["y"], batch["mask"])
        return metrics

    return TrainSpec(init_fn=init_fn, loss_fn=loss_fn, metrics_fn=metrics_fn,
                     name=name)


def make_segmentation_spec(model, example_x, num_classes,
                           ignore_index=255, name="segmentation",
                           aux_loss_weight=0.01):
    """Per-pixel cross-entropy over ``[B, H, W, C]`` logits with
    ignore-label masking (reference FedSeg ``MyModelTrainer`` loss). Metrics
    carry a summed ``[C, C]`` confusion matrix so the aggregator computes
    mIoU/FWIoU exactly (``fedseg/utils.py:246-288``)."""
    from fedml_tpu.core.seg_eval import confusion_matrix

    def init_fn(rng):
        return _init_state(model, example_x, rng)

    def _loss_and_metrics(logits, y, mask):
        y = y.astype(jnp.int32)
        pix_mask = ((y != ignore_index) & (y >= 0) &
                    (y < num_classes)).astype(jnp.float32)
        pix_mask = pix_mask * mask.reshape(mask.shape + (1,) * (y.ndim - 1))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        y_safe = jnp.clip(y, 0, logits.shape[-1] - 1)
        ll = jnp.take_along_axis(logp, y_safe[..., None], axis=-1)[..., 0]
        count = jnp.sum(pix_mask)
        loss = jnp.sum(-ll * pix_mask) / jnp.maximum(count, 1.0)
        pred = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((pred == y) * pix_mask)
        cm = confusion_matrix(jnp.where(pix_mask > 0, y, -1), pred,
                              num_classes)
        metrics = {"loss_sum": jnp.sum(-ll * pix_mask), "correct": correct,
                   "count": count, "confusion": cm}
        return loss, metrics

    def loss_fn(state, batch, rng, train):
        logits, new_state, aux = _apply_model(model, state, batch["x"],
                                              rng, train, with_sown=True)
        loss, metrics = _loss_and_metrics(logits, batch["y"], batch["mask"])
        return loss + aux_loss_weight * aux, (new_state, metrics)

    def metrics_fn(state, batch):
        logits, _ = _apply_model(model, state, batch["x"], None, False)
        _, metrics = _loss_and_metrics(logits, batch["y"], batch["mask"])
        return metrics

    return TrainSpec(init_fn=init_fn, loss_fn=loss_fn, metrics_fn=metrics_fn,
                     name=name)


def make_multilabel_spec(model, example_x, name="tag_prediction",
                         aux_loss_weight=0.01):
    """Sigmoid BCE multilabel (reference ``my_model_trainer_tag_prediction.py``
    for stackoverflow_lr: BCELoss + top-k precision/recall style counts)."""

    def init_fn(rng):
        return _init_state(model, example_x, rng)

    def _loss_and_metrics(probs, y, mask):
        probs = jnp.clip(probs.astype(jnp.float32), 1e-7, 1 - 1e-7)
        per_sample = -jnp.sum(y * jnp.log(probs) + (1 - y) * jnp.log(1 - probs),
                              axis=-1)
        count = jnp.sum(mask)
        loss = jnp.sum(per_sample * mask) / jnp.maximum(count, 1.0)
        pred = (probs > 0.5).astype(jnp.float32)
        tp = jnp.sum(pred * y * mask[:, None])
        fp = jnp.sum(pred * (1 - y) * mask[:, None])
        fn = jnp.sum((1 - pred) * y * mask[:, None])
        return loss, {"loss_sum": jnp.sum(per_sample * mask), "tp": tp,
                      "fp": fp, "fn": fn, "count": count,
                      "correct": tp}  # correct == true positives for acc parity

    def loss_fn(state, batch, rng, train):
        probs, new_state, aux = _apply_model(model, state, batch["x"],
                                             rng, train, with_sown=True)
        loss, metrics = _loss_and_metrics(probs, batch["y"], batch["mask"])
        return loss + aux_loss_weight * aux, (new_state, metrics)

    def metrics_fn(state, batch):
        probs, _ = _apply_model(model, state, batch["x"], None, False)
        _, metrics = _loss_and_metrics(probs, batch["y"], batch["mask"])
        return metrics

    return TrainSpec(init_fn=init_fn, loss_fn=loss_fn, metrics_fn=metrics_fn,
                     name=name)
