"""FedGKT: Group Knowledge Transfer (reference ``fedml_api/distributed/
fedgkt/``: clients train a small edge CNN and upload per-batch feature maps +
logits + labels; the server trains a large CNN on those features with
CE + temperature-KL distillation and returns per-client server logits --
``GKTClientTrainer.py:49-129``, ``GKTServerTrainer.py:101-120``, KL
temperature at ``GKTServerTrainer.py:48-49``).

TPU re-design: the client phase is the engine's vmapped local training with a
distillation-augmented loss; the feature-extraction pass and the server phase
are jitted scans. Pass ``mesh=`` (with a ``model`` axis,
``parallel.mesh.make_client_mesh(1, n)``) and the server phase runs under
``shard_map``: each step's sample batch splits over the ``model`` axis,
gradients are ``psum``-averaged and BN statistics ``pmean``-merged across
shards -- the TPU-native form of the reference's ``nn.DataParallel`` over 4
GPUs (``GKTServerTrainer.py:28-29``). ``evaluate()`` is one jitted program
scoring the combined edge->server pipeline over EVERY client's own
extractor and local test shard (the reference server tests on each
client's uploaded test features, ``GKTServerTrainer.py:216-244``).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from fedml_tpu.core.sharding import shard_map
from fedml_tpu.parallel.engine import ClientUpdateConfig, make_optimizer
from fedml_tpu.parallel.mesh import MODEL_AXIS
from fedml_tpu.parallel.packing import pack_cohort, pack_eval


def kl_divergence(student_logits, teacher_logits, T):
    """KL(softmax(teacher/T) || softmax(student/T)) * T^2 (Hinton
    distillation, reference ``utils.KL_Loss`` with temperature 3.0)."""
    t = jax.nn.softmax(teacher_logits.astype(jnp.float32) / T)
    log_s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / T)
    log_t = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / T)
    return jnp.sum(t * (log_t - log_s), axis=-1) * (T * T)


def _masked_ce(logits, y, mask):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(logp, y[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    return -ll * mask


class FedGKTAPI:
    """Args: ``temperature`` (default 3.0), ``alpha_distill`` (KL weight,
    default 1.0), ``epochs`` (client), ``server_epochs``."""

    def __init__(self, dataset, client_model, server_model, args,
                 mesh=None, metrics_logger=None):
        (_, _, _, self.test_data_global, _, self.train_data_local_dict,
         self.test_data_local_dict, self.class_num) = dataset
        self.args = args
        self.client_model = client_model
        self.server_model = server_model
        self.mesh = None
        if mesh is not None and MODEL_AXIS in mesh.axis_names:
            n_shards = mesh.shape[MODEL_AXIS]
            if n_shards > 1 and args.batch_size % n_shards:
                logging.warning(
                    "fedgkt: batch_size %d not divisible by %d model "
                    "shards; server phase runs unsharded",
                    args.batch_size, n_shards)
            elif n_shards > 1:
                self.mesh = mesh
        self.metrics_logger = metrics_logger or (lambda d: None)
        self.n_clients = len(self.train_data_local_dict)
        self.T = getattr(args, "temperature", 3.0)
        self.alpha = getattr(args, "alpha_distill", 1.0)
        self.server_epochs = getattr(args, "server_epochs", 1)

        cfg = ClientUpdateConfig(
            optimizer=getattr(args, "client_optimizer", "sgd"),
            lr=args.lr, weight_decay=getattr(args, "wd", 0.0))
        self.client_tx = make_optimizer(cfg)
        self.server_tx = make_optimizer(ClientUpdateConfig(
            optimizer=getattr(args, "server_optimizer_gkt", "sgd"),
            lr=getattr(args, "server_lr", args.lr),
            weight_decay=getattr(args, "wd", 0.0)))

        rng = jax.random.PRNGKey(getattr(args, "seed", 0))
        example = jnp.asarray(self.train_data_local_dict[0]["x"][:1])
        self.client_states = jax.vmap(
            lambda k: client_model.init(k, example, train=False)
        )(jax.random.split(jax.random.fold_in(rng, 1), self.n_clients))
        feats, _ = client_model.apply(
            jax.tree.map(lambda v: v[0], self.client_states), example,
            train=False)
        self.server_state = server_model.init(
            jax.random.fold_in(rng, 2), feats, train=False)
        self.server_opt = self.server_tx.init(self.server_state["params"])
        self.rng = rng
        self._data_rng = np.random.default_rng(getattr(args, "seed", 0))
        self.round_idx = 0
        # per-sample teacher logits [C, max_n, classes], aligned to each
        # client's canonical sample order -- round r's server logits are
        # scattered back by slot index so round r+1's reshuffled packing
        # gathers the teacher for the *same sample* (the reference keeps a
        # fixed extraction order for exactly this alignment)
        self._max_n = max(len(d["y"]) for d in self.train_data_local_dict.values())
        self.teacher_logits = np.zeros(
            (self.n_clients, self._max_n, self.class_num), np.float32)
        self.server_logits = None  # last round's per-slot server logits

        self._client_round = jax.jit(self._make_client_round())
        self._server_round = jax.jit(self._make_server_round())
        self._eval_fn = None  # built lazily (jitted all-client pipeline)

    # -- client phase ------------------------------------------------------
    def _make_client_round(self):
        cm, T, alpha = self.client_model, self.T, self.alpha
        tx = self.client_tx

        def one_client(state, data, teacher_logits, rng):
            params = state["params"]
            rest = {k: v for k, v in state.items() if k != "params"}
            opt = tx.init(params)

            def step(carry, xs):
                params, rest, opt = carry
                batch, t_logits = xs

                def loss_fn(p):
                    st = dict(rest); st["params"] = p
                    variables = dict(st)
                    (feats, logits), mut = cm.apply(
                        variables, batch["x"], train=True,
                        mutable=["batch_stats"])
                    ce = _masked_ce(logits, batch["y"], batch["mask"])
                    kl = kl_divergence(logits, t_logits, T) * batch["mask"]
                    count = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
                    loss = (jnp.sum(ce) + alpha * jnp.sum(kl)) / count
                    new_st = dict(st); new_st["batch_stats"] = mut["batch_stats"]
                    correct = jnp.sum(
                        (jnp.argmax(logits, -1) == batch["y"]) * batch["mask"])
                    return loss, (new_st, {"loss_sum": jnp.sum(ce),
                                           "correct": correct,
                                           "count": jnp.sum(batch["mask"])})

                (loss, (new_st, metrics)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                updates, new_opt = tx.update(grads, opt, params)
                new_params = optax.apply_updates(params, updates)
                valid = jnp.sum(batch["mask"]) > 0
                new_rest = {k: new_st[k] for k in rest}
                out = jax.tree.map(lambda a, b: jnp.where(valid, a, b),
                                   (new_params, new_rest, new_opt),
                                   (params, rest, opt))
                return out, metrics

            batches = {k: data[k] for k in ("x", "y", "mask")}
            (params, rest, _), metrics = jax.lax.scan(
                step, (params, rest, opt), (batches, teacher_logits))
            state = dict(rest); state["params"] = params

            # extraction pass: features + logits for every batch (eval mode)
            def extract(_, batch):
                feats, logits = cm.apply(state, batch["x"], train=False)
                return _, (feats, logits)

            _, (feats, logits) = jax.lax.scan(extract, 0, batches)
            msum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
            return state, feats, logits, msum

        def client_round(client_states, cohort, teacher_logits, rng):
            rngs = jax.random.split(rng, cohort["mask"].shape[0])
            return jax.vmap(one_client)(client_states, cohort,
                                        teacher_logits, rngs)

        return client_round

    # -- server phase ------------------------------------------------------
    def _make_server_round(self):
        sm, T, alpha = self.server_model, self.T, self.alpha
        tx = self.server_tx
        mesh = self.mesh

        n_epochs = self.server_epochs  # static under jit
        sharded = mesh is not None

        def server_round(server_state, server_opt, feats, client_logits,
                         ys, masks):
            """feats [C,S,B,h,w,c] pooled over clients; trains with
            CE + KL vs client logits, returns per-batch server logits.
            Under shard_map the B axis arrives pre-split over the ``model``
            mesh axis; sums/grads/BN stats are psum/pmean-merged so every
            shard steps identically (DataParallel semantics)."""
            C, S = feats.shape[0], feats.shape[1]
            flat = lambda a: a.reshape((C * S,) + a.shape[2:])
            fb, lb, yb, mb = flat(feats), flat(client_logits), flat(ys), flat(masks)

            def epoch(carry, _):
                state, opt = carry

                def step(carry2, xs):
                    state, opt = carry2
                    f, cl, y, m = xs

                    def loss_fn(p):
                        st = dict(state); st["params"] = p
                        logits, mut = sm.apply(st, f, train=True,
                                               mutable=["batch_stats"])
                        ce = _masked_ce(logits, y, m)
                        kl = kl_divergence(logits, cl, T) * m
                        # SUM form: normalized after the (possibly psummed)
                        # count so sharded and unsharded grads agree
                        loss_sum = jnp.sum(ce) + alpha * jnp.sum(kl)
                        new_st = dict(st)
                        if "batch_stats" in mut:
                            new_st["batch_stats"] = mut["batch_stats"]
                        return loss_sum, (new_st, jnp.sum(m))

                    (_, (new_st, cnt)), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(state["params"])
                    if sharded:
                        cnt = jax.lax.psum(cnt, MODEL_AXIS)
                        grads = jax.tree.map(
                            lambda g: jax.lax.psum(g, MODEL_AXIS), grads)
                        if "batch_stats" in new_st:
                            new_st = dict(new_st)
                            new_st["batch_stats"] = jax.tree.map(
                                lambda s: jax.lax.pmean(s, MODEL_AXIS),
                                new_st["batch_stats"])
                    grads = jax.tree.map(
                        lambda g: g / jnp.maximum(cnt, 1.0), grads)
                    updates, new_opt = tx.update(grads, opt, state["params"])
                    new_params = optax.apply_updates(state["params"], updates)
                    new_state = dict(new_st); new_state["params"] = new_params
                    valid = cnt > 0
                    out = jax.tree.map(lambda a, b: jnp.where(valid, a, b),
                                       (new_state, new_opt), (state, opt))
                    return out, ()

                (state, opt), _ = jax.lax.scan(step, (state, opt),
                                               (fb, lb, yb, mb))
                return (state, opt), 0.0

            (server_state, server_opt), _ = jax.lax.scan(
                epoch, (server_state, server_opt), jnp.arange(n_epochs))

            # produce fresh server logits for each client batch (teacher signal)
            def infer(_, xs):
                f, _m = xs
                logits = sm.apply(server_state, f, train=False)
                return _, logits

            _, out_logits = jax.lax.scan(infer, 0, (fb, mb))
            out_logits = out_logits.reshape((C, S) + out_logits.shape[1:])
            return server_state, server_opt, out_logits

        if not sharded:
            return server_round

        # batch-dim sharding over the `model` axis: [C,S,B,...] splits on
        # axis 2; model/optimizer state replicated; logits return sharded
        # on their B axis and reassemble transparently
        data_spec = P(None, None, MODEL_AXIS)
        return shard_map(
            server_round, mesh=mesh,
            in_specs=(P(), P(), data_spec, data_spec, data_spec, data_spec),
            out_specs=(P(), P(), data_spec),
            check_vma=False)

    def train_one_round(self):
        packed = pack_cohort(
            [self.train_data_local_dict[i] for i in range(self.n_clients)],
            self.args.batch_size, self.args.epochs, rng=self._data_rng,
            return_indices=True)
        # gather per-sample teacher logits into this round's slot layout
        ci = np.arange(self.n_clients)[:, None, None]
        teacher = jnp.asarray(self.teacher_logits[ci, packed["idx"]])
        self.rng, rng = jax.random.split(self.rng)
        self.client_states, feats, logits, metrics = self._client_round(
            self.client_states, packed, teacher, rng)
        self.server_state, self.server_opt, self.server_logits = \
            self._server_round(self.server_state, self.server_opt, feats,
                               logits, jnp.asarray(packed["y"]),
                               jnp.asarray(packed["mask"]))
        # scatter fresh server logits back to per-sample alignment
        sl = np.asarray(self.server_logits, np.float32)
        m = packed["mask"] > 0
        client_ids = np.broadcast_to(ci, m.shape)[m]
        self.teacher_logits[client_ids, packed["idx"][m]] = sl[m]
        m = jax.tree.map(np.asarray, metrics)
        out = {"round": self.round_idx,
               "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
               "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1))}
        self.round_idx += 1
        self.metrics_logger(out)
        return out

    def _make_eval(self):
        cm, sm = self.client_model, self.server_model

        @jax.jit
        def eval_fn(client_states, server_state, data):
            def one_client(cstate, d):
                def step(_, batch):
                    feats, _l = cm.apply(cstate, batch["x"], train=False)
                    logits = sm.apply(server_state, feats, train=False)
                    correct = jnp.sum(
                        (jnp.argmax(logits, -1) == batch["y"]) * batch["mask"])
                    return _, {"correct": correct,
                               "count": jnp.sum(batch["mask"])}

                _, ms = jax.lax.scan(step, 0, d)
                return jax.tree.map(jnp.sum, ms)

            ms = jax.vmap(one_client)(client_states, data)
            return jax.tree.map(jnp.sum, ms)

        return eval_fn

    def evaluate(self):
        """End-to-end eval of the combined edge->server pipeline, one jitted
        program over ALL clients: each client's own extractor feeds the
        server model on that client's local test shard (reference
        ``GKTServerTrainer`` tests on every client's uploaded test
        features). Falls back to the global test set routed through every
        extractor when local shards are absent."""
        if self._eval_fn is None:
            self._eval_fn = self._make_eval()
        shards, sel = [], []
        for i in range(self.n_clients):
            d = self.test_data_local_dict.get(i)
            if d is not None and len(d["y"]):
                shards.append(d)
                sel.append(i)
        if not shards:
            shards = [self.test_data_global] * self.n_clients
            sel = list(range(self.n_clients))
        packs = [pack_eval(d, self.args.batch_size) for d in shards]
        S = max(p["mask"].shape[0] for p in packs)

        def pad(p):
            w = S - p["mask"].shape[0]
            return {k: np.concatenate(
                [v, np.zeros((w,) + v.shape[1:], v.dtype)]) if w else v
                for k, v in p.items()}

        data = jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)),
                            *[pad(p) for p in packs])
        states = jax.tree.map(lambda v: v[np.asarray(sel)],
                              self.client_states)
        m = jax.tree.map(np.asarray,
                         self._eval_fn(states, self.server_state, data))
        return {"Test/Acc": float(m["correct"] / max(m["count"], 1)),
                "Test/Samples": float(m["count"]),
                "Test/Correct": float(m["correct"])}

    def train(self):
        for _ in range(self.args.comm_round):
            out = self.train_one_round()
        return out

