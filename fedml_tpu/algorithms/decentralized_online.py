"""Decentralized *online* learning over streaming data (DSGD / PushSum).

Parity: reference ``fedml_api/standalone/decentralized/`` -- online
logistic regression over streaming UCI data (SUSY / Room Occupancy), one
sample per node per time step, gossip averaging over a (possibly
time-varying / directed) topology, evaluated by average online loss and
regret (``decentralized_fl_api.py:20-99``, ``client_pushsum.py:7-129``,
``client_dsgd.py``).

TPU design: instead of N Python client objects exchanging messages per
step, the whole horizon is ONE jitted program -- node states stacked
``[N, d]``, streams stacked ``[N, T, d]``, and ``lax.scan`` over time with
a matmul mixing step (``W @ states``, the dense-mesh analog of neighbor
``ppermute``). Predict-then-update ordering gives the true online loss the
regret definition requires.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.topology import SymmetricTopologyManager


def _col_stochastic(W):
    support = (np.asarray(W) > 0).astype(np.float32)
    return support / support.sum(axis=0, keepdims=True)


class DecentralizedOnlineAPI:
    """Online DSGD / PushSum over per-node streams.

    Args:
      streams: ``{node_id: {"x": [T_i, d], "y": [T_i]}}`` (uci loaders /
        ``load_synthetic_stream``). Horizon T = min_i T_i (reference
        iterates the common stream length).
      args: ``lr``, ``comm_round`` unused here; ``time_varying`` (bool)
        regenerates the gossip matrix each step from a folded seed.
      algorithm: "dsgd" (symmetric, row-stochastic) or "pushsum"
        (directed, column-stochastic with de-biasing weights).
    """

    def __init__(self, streams, args, topology=None, algorithm="dsgd",
                 metrics_logger=None):
        self.n_nodes = len(streams)
        self.algorithm = algorithm
        self.args = args
        self.metrics_logger = metrics_logger or (lambda d: logging.info("%s", d))
        T = min(len(s["y"]) for s in streams.values())
        d = streams[0]["x"].shape[1]
        self.T, self.d = T, d
        self.x = jnp.asarray(np.stack(
            [np.asarray(streams[i]["x"][:T]) for i in range(self.n_nodes)]))
        self.y = jnp.asarray(np.stack(
            [np.asarray(streams[i]["y"][:T]) for i in range(self.n_nodes)]))

        tm = topology or SymmetricTopologyManager(
            self.n_nodes, neighbor_num=getattr(args, "topology_neighbors", 2),
            seed=getattr(args, "seed", 0))
        if tm.topology is None:
            tm.generate_topology()
        W = np.asarray(tm.topology, np.float32)
        if algorithm == "pushsum":
            W = _col_stochastic(W)
        self.W = jnp.asarray(W)
        self.time_varying = bool(getattr(args, "time_varying", False))
        lr = args.lr

        def step(carry, inputs):
            w, omega, key = carry
            x_t, y_t = inputs  # [N, d], [N]
            # predict with the de-biased iterate (PushSum) or raw (DSGD)
            z = w / omega[:, None] if algorithm == "pushsum" else w
            logits = jnp.sum(z * x_t, axis=1)
            probs = jax.nn.sigmoid(logits)
            loss = -(y_t * jnp.log(probs + 1e-8) +
                     (1 - y_t) * jnp.log(1 - probs + 1e-8))
            correct = ((probs > 0.5) == (y_t > 0.5)).astype(jnp.float32)
            grad = (probs - y_t)[:, None] * x_t  # d/dw of logistic loss

            if self.time_varying:
                key, sub = jax.random.split(key)
                perm = jax.random.permutation(sub, self.n_nodes)
                W_t = self.W[perm][:, perm]
            else:
                W_t = self.W
            # local gradient step, then PUSH-based gossip: sender i ships
            # x_i weighted by ITS row entry W[i, j], receiver j sums --
            # x' = W^T x (``client_dsgd.py:78-103``: topo_weight is the
            # sender's row value). For the column-stochastic PushSum matrix
            # the push form is x' = W x by construction. Row-form W @ x
            # (in-neighbor averaging) is the OTHER reference DSGD
            # (decentralized_framework) and lives in decentralized.py.
            stepped = w - lr * grad
            w_mixed = (W_t @ stepped if algorithm == "pushsum"
                       else W_t.T @ stepped)
            if algorithm == "pushsum":
                omega = W_t @ omega
            return (w_mixed, omega, key), (loss, correct)

        @jax.jit
        def run(w0, omega0, key):
            (wT, omegaT, _), (losses, corrects) = jax.lax.scan(
                step, (w0, omega0, key),
                (jnp.swapaxes(self.x, 0, 1), jnp.swapaxes(self.y, 0, 1)))
            return wT, omegaT, losses, corrects

        self._run = run

    def train(self):
        """Run the full horizon; returns per-node final models and logs
        average online loss / accuracy / regret-per-step."""
        w0 = jnp.zeros((self.n_nodes, self.d))
        omega0 = jnp.ones((self.n_nodes,))
        key = jax.random.PRNGKey(getattr(self.args, "seed", 0))
        wT, omegaT, losses, corrects = self._run(w0, omega0, key)
        self.w = np.asarray(wT / omegaT[:, None]
                            if self.algorithm == "pushsum" else wT)
        losses = np.asarray(losses)      # [T, N]
        corrects = np.asarray(corrects)  # [T, N]
        self.history = {
            "Online/AvgLoss": float(losses.mean()),
            "Online/AvgAcc": float(corrects.mean()),
            # reference ``cal_regret`` (decentralized_fl_api.py:11-17):
            # cumulative loss / (client_number * (t+1)) at the final step
            "Online/Regret": float(losses.sum() /
                                   (losses.shape[1] * losses.shape[0])),
            "Online/FinalConsensus": float(
                np.linalg.norm(self.w - self.w.mean(0, keepdims=True)) /
                max(1, self.n_nodes)),
        }
        self.metrics_logger(self.history)
        return self.w

    def consensus_distance(self):
        w = self.w
        return float(np.mean(np.linalg.norm(
            w - w.mean(0, keepdims=True), axis=1)))
