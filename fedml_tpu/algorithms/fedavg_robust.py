"""Robust FedAvg: defenses against poisoning (reference
``fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:10-130`` +
``fedml_core/robustness/robust_aggregation.py``).

Defense = per-client norm-difference clipping of the update (before the
weighted average) + weak-DP Gaussian noise on the aggregate -- both pure
pytree ops running on-device inside the round. Backdoor-accuracy evaluation
uses the poisoned test set from ``fedml_tpu.data.poison``.
"""

from __future__ import annotations

import jax

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.robust import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.parallel.packing import pack_eval


def make_robust_hooks(norm_bound, stddev):
    def payload_fn(local_state, global_state, aux):
        return norm_diff_clipping(local_state, global_state, norm_bound)

    def server_fn(global_state, avg_state, server_state, rng):
        if stddev and stddev > 0:
            avg_state = add_gaussian_noise(avg_state, stddev, rng)
        return avg_state, server_state

    return payload_fn, server_fn


class FedAvgRobustAPI(FedAvgAPI):
    """Extra args (reference ``main_fedavg_robust.py:56-83``):
    ``norm_bound`` (clip radius), ``stddev`` (weak-DP noise); the poisoned
    dataset itself comes from the data layer (``--poison_type`` etc.)."""

    def __init__(self, dataset, spec, args, mesh=None, metrics_logger=None,
                 poisoned_test_data=None):
        payload_fn, server_fn = make_robust_hooks(
            getattr(args, "norm_bound", 30.0),
            getattr(args, "stddev", 0.025))
        super().__init__(dataset, spec, args, mesh=mesh,
                         payload_fn=payload_fn, server_fn=server_fn,
                         metrics_logger=metrics_logger)
        self.poisoned_test_data = poisoned_test_data

    def evaluate_backdoor(self):
        """Attack success rate on the poisoned test set (reference
        ``test_target_accuracy``, ``FedAvgRobustAggregator.py:14-111``)."""
        if self.poisoned_test_data is None:
            return {}
        import numpy as np
        packed = pack_eval(self.poisoned_test_data, self.args.batch_size)
        m = jax.tree.map(np.asarray, self.eval_fn(self.global_state, packed))
        return {"Backdoor/Acc": float(m["correct"] / max(m["count"], 1))}
