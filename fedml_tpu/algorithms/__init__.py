"""FL algorithms on the common round engine.

Each module provides aggregator hooks (payload_fn / server_fn) and a
user-facing API class matching the reference's per-algorithm surface
(SURVEY.md sections 2.2-2.3).
"""

from fedml_tpu.algorithms.specs import (  # noqa: F401
    make_classification_spec,
    make_seq_classification_spec,
)
from fedml_tpu.algorithms.fedavg import FedAvgAPI  # noqa: F401
