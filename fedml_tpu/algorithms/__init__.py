"""FL algorithms on the common round engine.

Each module provides aggregator hooks (payload_fn / server_fn) and a
user-facing API class matching the reference's per-algorithm surface
(SURVEY.md sections 2.2-2.3).
"""

from fedml_tpu.algorithms.specs import (  # noqa: F401
    make_classification_spec,
    make_seq_classification_spec,
    make_multilabel_spec,
)
from fedml_tpu.algorithms.fedavg import FedAvgAPI  # noqa: F401
from fedml_tpu.algorithms.fedopt import FedOptAPI  # noqa: F401
from fedml_tpu.algorithms.fednova import FedNovaAPI  # noqa: F401
from fedml_tpu.algorithms.fedavg_robust import FedAvgRobustAPI  # noqa: F401
from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI  # noqa: F401
from fedml_tpu.algorithms.decentralized import DecentralizedFedAPI  # noqa: F401
from fedml_tpu.algorithms.splitnn import SplitNNAPI  # noqa: F401
from fedml_tpu.algorithms.fedgkt import FedGKTAPI  # noqa: F401
from fedml_tpu.algorithms.vertical import VerticalFLAPI  # noqa: F401
from fedml_tpu.algorithms.turboaggregate import TurboAggregateAPI  # noqa: F401
from fedml_tpu.algorithms.fednas import FedNASAPI, FedNASConfig  # noqa: F401
