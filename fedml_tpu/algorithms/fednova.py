"""FedNova: normalized averaging (reference
``fedml_api/standalone/fednova/fednova.py:10-71`` + ``fednova_trainer.py:
97-109``).

Each client reports its normalized update direction ``d_i = (global - local)
/ tau_i`` (tau_i = executed local steps); the server applies
``global -= tau_eff * sum_i p_i d_i`` with ``tau_eff = sum_i p_i tau_i``,
removing the objective inconsistency caused by heterogeneous local step
counts. Both the per-client normalization and tau_eff flow through the
engine's single weighted mean: the payload carries ``{"d": d_i, "tau": tau_i}``
and its n_i-weighted average is exactly ``{sum p_i d_i, tau_eff}``.
"""

from __future__ import annotations

import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core import pytree


def fednova_payload(local_state, global_state, aux):
    tau = jnp.maximum(aux["steps"].astype(jnp.float32), 1.0)
    d = pytree.tree_scale(
        pytree.tree_sub(global_state["params"], local_state["params"]),
        1.0 / tau)
    rest = {k: v for k, v in local_state.items() if k != "params"}
    return {"d": d, "tau": tau, "rest": rest}


def fednova_server(global_state, avg_payload, server_state, rng):
    tau_eff = avg_payload["tau"]
    new_params = pytree.tree_sub(
        global_state["params"],
        pytree.tree_scale(avg_payload["d"], tau_eff))
    new_global = dict(avg_payload["rest"])
    new_global["params"] = new_params
    return new_global, server_state


class FedNovaAPI(FedAvgAPI):
    def __init__(self, dataset, spec, args, mesh=None, metrics_logger=None):
        super().__init__(dataset, spec, args, mesh=mesh,
                         payload_fn=fednova_payload, server_fn=fednova_server,
                         metrics_logger=metrics_logger)
