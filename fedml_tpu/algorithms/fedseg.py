"""FedSeg: federated semantic segmentation.

Parity: reference ``fedml_api/distributed/fedseg/`` -- FedAvg over a
DeepLab-style model with (a) mIoU/FWIoU confusion-matrix evaluation
(``FedSegAggregator.py:12-43``, ``utils.py:246-288``), (b) cos/poly/step
LR schedules with warmup (``utils.py:114-165``), and (c) best-metric
checkpointing via ``Saver`` (``utils.py:169-242``) -- here supplied by
``fedml_tpu.utils.Checkpointer`` in the experiment main.

The round engine is the shared FedAvg engine; only the task spec
(per-pixel CE + confusion metrics) and the evaluation differ. The
confusion matrix is accumulated on device inside the jitted eval scan and
crosses to host once per eval.
"""

from __future__ import annotations

import argparse
import math

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.seg_eval import Evaluator
from fedml_tpu.utils.schedules import make_lr_schedule


class FedSegAPI(FedAvgAPI):
    """FedAvg loop + segmentation eval + reference LR schedules.

    Extra args (reference fedseg flags): ``lr_scheduler`` (cos|poly|step),
    ``lr_step``, ``warmup_epochs``.
    """

    def __init__(self, dataset, spec, args, mesh=None, metrics_logger=None):
        mode = getattr(args, "lr_scheduler", None)
        if mode:
            # horizon from the LARGEST shard so no client's valid steps
            # outrun the schedule (smaller clients just stop mid-decay)
            sizes = [len(d["y"]) for d in dataset[5].values()
                     if d is not None and len(d["y"])]
            iters = max(1, math.ceil(max(sizes) / args.batch_size))
            schedule = make_lr_schedule(
                mode, args.lr, args.epochs, iters,
                lr_step=getattr(args, "lr_step", 0),
                warmup_epochs=getattr(args, "warmup_epochs", 0))
            args = argparse.Namespace(**{**vars(args), "lr": schedule})
        super().__init__(dataset, spec, args, mesh=mesh,
                         metrics_logger=metrics_logger)
        self.num_classes = dataset[7]
        self.checkpoint_metric = "Seg/mIoU"

    def evaluate_global(self):
        m = jax.tree.map(np.asarray, self.eval_fn(
            self.global_state, self._packed_global_eval()))
        ev = Evaluator(self.num_classes)
        ev.add_matrix(m["confusion"])
        out = {"Test/Loss": float(m["loss_sum"] / max(m["count"], 1)),
               "Test/Acc": float(m["correct"] / max(m["count"], 1))}
        out.update(ev.metrics())
        return out

    def train_one_round(self):
        metrics = super().train_one_round()
        # per-round train confusion rides the summed-metrics pytree
        cm = np.asarray(self._last_metrics["confusion"])
        while cm.ndim > 2:  # per-client leading axes in the sim path
            cm = cm.sum(axis=0)
        ev = Evaluator(self.num_classes)
        ev.add_matrix(cm)
        metrics["Train/mIoU"] = ev.mean_iou()
        return metrics
