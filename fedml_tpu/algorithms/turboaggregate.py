"""TurboAggregate: secure aggregation for FedAvg (reference
``fedml_api/distributed/turboaggregate/``: Lagrange/BGW MPC primitives in
``mpc_function.py`` + a plain weighted-average aggregator in
``TA_Aggregator.py:56-85`` -- the shipped aggregate is FedAvg in the clear,
with the MPC machinery alongside; SURVEY.md section 2.2).

Here the local-training phase runs on-device via the shared engine, and the
aggregation phase runs through the additive-masking secure sum
(``fedml_tpu.core.mpc.secure_aggregate``): the server only ever combines
masked shares, never an individual client's update.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core import mpc
from fedml_tpu.parallel.engine import make_client_update


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg loop with the aggregation step replaced by a secure masked sum.
    Extra args: ``mpc_scale`` (fixed-point scale, default 2**16)."""

    def __init__(self, dataset, spec, args, metrics_logger=None):
        super().__init__(dataset, spec, args, metrics_logger=metrics_logger)
        self._client_update = jax.jit(
            jax.vmap(make_client_update(spec, self.cfg),
                     in_axes=(None, 0, 0)))
        self.mpc_scale = getattr(args, "mpc_scale", 2 ** 16)
        # the masking stream: derived from the run seed through the MPC
        # salt (mpc.mask_rng), never an unseeded or constant default
        self._mpc_rng = mpc.mask_rng(getattr(args, "seed", 0))

    def train_one_round(self):
        t0 = time.time()
        _, packed = self._cohort(self.round_idx)
        self.rng, round_rng = jax.random.split(self.rng)
        C = packed["mask"].shape[0]
        rngs = jax.random.split(round_rng, C)
        local_states, aux, metrics = self._client_update(
            self.global_state, packed, rngs)

        # host-side secure aggregation of n_i-weighted updates; float64 is
        # deliberate: sample counts are exact integers and the fixed-point
        # encode/decode needs the full 53-bit mantissa for the weight
        # normalization to round-trip (FL105's device-code concern does
        # not apply on the host path)
        ns = np.asarray(aux["n"], np.float64)  # fedlint: disable=FL105
        total_n = max(ns.sum(), 1e-12)
        leaves, treedef = jax.tree.flatten(
            jax.tree.map(np.asarray, local_states))
        agg_leaves = []
        for leaf_idx in range(len(leaves)):
            weighted = [leaves[leaf_idx][c] * (ns[c] / total_n)
                        for c in range(C)]
            agg = mpc.secure_aggregate(weighted, scale=self.mpc_scale,
                                       rng=self._mpc_rng)
            agg_leaves.append(agg.astype(leaves[leaf_idx].dtype))
        self.global_state = jax.tree.unflatten(treedef, agg_leaves)

        m = jax.tree.map(np.asarray, metrics)
        out = {"round": self.round_idx,
               "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
               "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1)),
               "round_time_s": time.time() - t0}
        self.round_idx += 1
        return out
