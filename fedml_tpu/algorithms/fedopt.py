"""FedOpt: server-side adaptive optimization (reference
``fedml_api/distributed/fedopt/FedOptAggregator.py:91-122``).

The reference averages client weights, treats ``global - avg`` as a
pseudo-gradient, and feeds it to a reflected ``torch.optim`` subclass
(``optrepo.py:7-64``). Here the server optimizer is an optax transformation
applied inside the jitted round -- ``get_server_optimizer`` replaces the
OptRepo reflection registry.
"""

from __future__ import annotations

import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core import pytree


def get_server_optimizer(name, lr, momentum=0.9, **kw):
    """Name -> optax transformation (reference ``--server_optimizer`` flag,
    ``main_fedopt.py:54-60``; FedAvgM = sgd+momentum, FedAdam, FedAdagrad per
    'Adaptive Federated Optimization', arXiv:2003.00295)."""
    name = name.lower()
    if name in ("sgd", "fedavgm"):
        return optax.sgd(lr, momentum=momentum)
    if name in ("adam", "fedadam"):
        return optax.adam(lr, b1=kw.get("b1", 0.9), b2=kw.get("b2", 0.99),
                          eps=kw.get("eps", 1e-3))
    if name in ("adagrad", "fedadagrad"):
        return optax.adagrad(lr, eps=kw.get("eps", 1e-3))
    if name in ("yogi", "fedyogi"):
        return optax.yogi(lr)
    raise ValueError(f"unknown server optimizer: {name}")


def make_fedopt_hooks(server_tx):
    """Aggregator hooks implementing the pseudo-gradient server step."""

    def payload_fn(local_state, global_state, aux):
        return local_state

    def server_fn(global_state, avg_state, server_opt_state, rng):
        pseudo_grad = pytree.tree_sub(global_state["params"],
                                      avg_state["params"])
        updates, new_opt_state = server_tx.update(
            pseudo_grad, server_opt_state, global_state["params"])
        new_params = optax.apply_updates(global_state["params"], updates)
        new_global = dict(avg_state)  # batch_stats et al. take the average
        new_global["params"] = new_params
        return new_global, new_opt_state

    return payload_fn, server_fn


class FedOptAPI(FedAvgAPI):
    """FedAvg loop + server optimizer (reference ``fedopt_api.py:62-109``).
    Extra args: ``server_optimizer`` (default ``sgd``), ``server_lr``
    (default 1.0), ``server_momentum``.

    Resilience (``--overselect`` / ``--straggler_p`` / ``--quorum``)
    composes through the inherited round loop: the pseudo-gradient is
    ``global - avg`` where ``avg`` is already the renormalized average
    over the *reporting* subset, so a degraded round steps the server
    optimizer on the surviving cohort's consensus -- exactly the
    Bonawitz-style partial aggregate, never a zero-biased one."""

    def __init__(self, dataset, spec, args, mesh=None, metrics_logger=None,
                 compressor=None):
        server_tx = get_server_optimizer(
            getattr(args, "server_optimizer", "sgd"),
            getattr(args, "server_lr", 1.0),
            momentum=getattr(args, "server_momentum", 0.9))
        payload_fn, server_fn = make_fedopt_hooks(server_tx)
        # compressor= composes transparently: the compressed round feeds
        # RECONSTRUCTED client states through payload_fn, so the server
        # optimizer steps on the pseudo-gradient that survived compression
        super().__init__(dataset, spec, args, mesh=mesh,
                         payload_fn=payload_fn, server_fn=server_fn,
                         metrics_logger=metrics_logger, compressor=compressor)
        self.server_state = server_tx.init(self.global_state["params"])
