"""Decentralized (serverless) FL: gossip averaging over a topology.

Two reference behaviors reproduced:
- DSGD neighbor-mixing of model parameters (reference
  ``fedml_api/distributed/decentralized_framework`` send-to-out-neighbors /
  barrier-on-in-neighbors protocol, ``decentralized_worker_manager.py:29-46``),
  generalized to the weighted mixing matrix of the topology managers.
- PushSum for directed (asymmetric) topologies (reference
  ``fedml_api/standalone/decentralized/client_pushsum.py:7-129``): nodes gossip
  ``(w * x, w)`` pairs and de-bias by the scalar weight.

TPU mapping: node models are a stacked pytree ``[N, ...]``; one gossip step is
``einsum('ij,j...->i...', W, states)`` -- XLA lowers the mixing to MXU matmuls
on one chip, and on a mesh each node shard gathers only its in-neighbor rows
(here via all_gather; a ppermute ring specialization applies when W is a
ring, reference topology's default).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.topology import SymmetricTopologyManager
from fedml_tpu.parallel.engine import ClientUpdateConfig, make_client_update
from fedml_tpu.parallel.packing import pack_cohort


def mix_states(stacked_states, W):
    """One gossip mixing step: state_i <- sum_j W[i, j] state_j."""
    W = jnp.asarray(W, jnp.float32)
    return jax.tree.map(
        lambda x: jnp.einsum("ij,j...->i...", W,
                             x.astype(jnp.float32)).astype(x.dtype),
        stacked_states)


class DecentralizedFedAPI:
    """Serverless training loop: every node trains locally each round, then
    mixes with its topology neighbors (DSGD) or runs PushSum de-biased gossip
    on directed graphs."""

    def __init__(self, dataset, spec, args, topology=None, algorithm="dsgd",
                 metrics_logger=None, compressor=None):
        (self.train_data_num, _, self.train_data_global, self.test_data_global,
         _, self.train_data_local_dict, self.test_data_local_dict,
         self.class_num) = dataset
        self.spec = spec
        self.args = args
        self.algorithm = algorithm
        self.n_nodes = len(self.train_data_local_dict)
        tm = topology or SymmetricTopologyManager(
            self.n_nodes, neighbor_num=getattr(args, "topology_neighbors", 2),
            seed=getattr(args, "seed", 0))
        if tm.topology is None:
            tm.generate_topology()
        W = np.asarray(tm.topology, np.float32)
        if algorithm == "pushsum":
            # PushSum requires a COLUMN-stochastic matrix (each sender splits
            # its mass over out-neighbors); the topology managers are
            # row-stochastic, which would make the de-biasing weight a no-op
            # and leave the stationary-distribution bias in place.
            support = (W > 0).astype(np.float32)
            W = support / support.sum(axis=0, keepdims=True)
        self.W = W
        self.metrics_logger = metrics_logger or (lambda d: None)

        cfg = ClientUpdateConfig(
            optimizer=getattr(args, "client_optimizer", "sgd"),
            lr=args.lr, weight_decay=getattr(args, "wd", 0.0),
            momentum=getattr(args, "momentum", 0.0))
        client_update = make_client_update(spec, cfg)

        from fedml_tpu.compression import get_compressor
        self.compressor = get_compressor(
            compressor if compressor is not None
            else getattr(args, "compressor", None))
        compressor_ = self.compressor

        def round_fn(stacked_states, pushsum_w, residuals, cohort_data, W,
                     rng):
            N = cohort_data["mask"].shape[0]
            rngs = jax.random.split(rng, N)
            local_states, aux, metrics = jax.vmap(client_update)(
                stacked_states, cohort_data, rngs)
            if compressor_ is not None:
                # each node gossips its COMPRESSED params update (delta
                # from its pre-round state) with per-node error feedback --
                # what a bandwidth-limited peer link would deliver; mixing
                # then runs on the reconstructed states. Only ``params``
                # is compressed: batch_stats/other state is small and
                # bias-sensitive (a sign-compressed variance delta can go
                # negative), same split as the FedAvg compressed round.
                from fedml_tpu.compression.compressors import ErrorFeedback
                from fedml_tpu.core import pytree as ptu
                ef = ErrorFeedback(compressor_)
                crngs = jax.random.split(jax.random.fold_in(rng, 1), N)

                def compress_one(prev, local, res, crng):
                    delta = ptu.tree_sub(local["params"], prev["params"])
                    _, dec, new_res = ef.step(delta, res, prev["params"],
                                              crng)
                    recon = dict(local)
                    recon["params"] = ptu.tree_add(prev["params"], dec)
                    return recon, new_res

                local_states, residuals = jax.vmap(compress_one)(
                    stacked_states, local_states, residuals, crngs)
            if self.algorithm == "pushsum":
                # gossip (w_j * x_j, w_j) along columns, then de-bias
                weighted = jax.tree.map(
                    lambda x: x * pushsum_w.reshape((-1,) + (1,) * (x.ndim - 1)),
                    local_states)
                mixed = mix_states(weighted, W)
                new_w = W @ pushsum_w
                new_states = jax.tree.map(
                    lambda x: x / new_w.reshape((-1,) + (1,) * (x.ndim - 1)),
                    mixed)
                return new_states, new_w, residuals, metrics
            mixed = mix_states(local_states, W)
            return mixed, pushsum_w, residuals, metrics

        self._round_fn = jax.jit(round_fn, donate_argnums=(0, 1, 2))

        self.rng = jax.random.PRNGKey(getattr(args, "seed", 0))
        init = spec.init_fn(jax.random.fold_in(self.rng, 0))
        # all nodes start from the same init (reference broadcasts rank 0 init)
        self.states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.n_nodes,) + x.shape), init)
        # per-node error-feedback residuals over params only (what gets
        # compressed); uncompressed runs thread an empty pytree instead of
        # a second copy of node state (the compressor is fixed at trace
        # time, so the branch is static)
        self.residuals = (jax.tree.map(jnp.zeros_like, self.states["params"])
                          if self.compressor is not None else {})
        self.pushsum_w = jnp.ones((self.n_nodes,), jnp.float32)
        self._data_rng = np.random.default_rng(getattr(args, "seed", 0))
        self.round_idx = 0
        self.history = []

    def train_one_round(self):
        packed = pack_cohort(
            [self.train_data_local_dict[i] for i in range(self.n_nodes)],
            self.args.batch_size, self.args.epochs, rng=self._data_rng)
        self.rng, rng = jax.random.split(self.rng)
        self.states, self.pushsum_w, self.residuals, metrics = self._round_fn(
            self.states, self.pushsum_w, self.residuals, packed, self.W, rng)
        m = jax.tree.map(np.asarray, metrics)
        out = {"round": self.round_idx,
               "Train/Loss": float(m["loss_sum"].sum() / max(m["count"].sum(), 1)),
               "Train/Acc": float(m["correct"].sum() / max(m["count"].sum(), 1))}
        if self.compressor is not None:
            from fedml_tpu.compression import (compressed_payload_nbytes,
                                               raw_payload_nbytes)
            if not hasattr(self, "_payload_bytes"):
                node0 = jax.tree.map(lambda x: x[0], self.states)
                rest = {k: v for k, v in node0.items() if k != "params"}
                # compressed params + any uncompressed non-params state
                # (batch_stats etc. gossip at full fidelity)
                self._payload_bytes = compressed_payload_nbytes(
                    self.compressor, node0["params"]) + (
                        raw_payload_nbytes(rest) if rest else 0)
                self._raw_payload_bytes = raw_payload_nbytes(node0)
            # each node ships one compressed update to its out-neighbors;
            # count one send per node (broadcast links dedupe per edge)
            out["bytes_on_wire"] = self._payload_bytes * self.n_nodes
            out["compression_ratio"] = round(
                self._raw_payload_bytes / self._payload_bytes, 3)
        self.round_idx += 1
        self.history.append(out)
        self.metrics_logger(out)
        return out

    def consensus_distance(self):
        """Mean squared distance of node models from their average -- the
        convergence diagnostic for gossip algorithms."""
        mean_state = jax.tree.map(lambda x: jnp.mean(x, axis=0), self.states)
        sq = jax.tree.map(
            lambda x, mu: jnp.mean(jnp.sum((x - mu[None]) ** 2,
                                           axis=tuple(range(1, x.ndim)))),
            self.states, mean_state)
        return float(sum(jax.tree.leaves(sq)))

    def node_state(self, i):
        return jax.tree.map(lambda x: x[i], self.states)

    def train(self):
        for _ in range(self.args.comm_round):
            self.train_one_round()
        return self.states
