"""XLA cost-model performance attribution: FLOPs from the program run.

Until now every MFU number in this repo came from a hand-maintained
analytic constant (``bench.py TRAIN_FLOPS_PER_SAMPLE``: ResNet-56 MACs
counted off the reference topology, times the 3x fwd/bwd rule of thumb).
That constant silently rots the moment the model, the lowering, or the
augmentation pipeline changes. XLA already knows what it compiled:
``lowered.compile().cost_analysis()`` reports FLOPs and bytes accessed
for the exact HLO the device executes. This module turns that into the
repo's FLOPs source of record:

- :func:`program_cost` -- cost of one jitted callable at given arg
  shapes (``ShapeDtypeStruct`` args work: no allocation, no execution).
- :func:`train_step_cost` -- cost of ONE local-SGD training step built
  from a ``TrainSpec`` + ``ClientUpdateConfig`` exactly the way the
  engine's trip-loop builds it (value_and_grad + optimizer update +
  the spec's augmentation), so per-sample train FLOPs come from the
  program actually run. ``bench.py`` divides by the batch size for its
  MFU; the analytic constant remains as the cross-checked fallback
  (``tests/test_observability.py`` pins agreement within the tolerance
  documented in docs/PERFORMANCE.md round 7).
- :class:`CostModel` -- a default-OFF process global (same switchboard
  discipline as the tracer/registry/recorder): when armed,
  ``BucketedStreamRunner`` attributes per-bucket-shape FLOPs and
  FLOP-weighted padding waste into its round info, and the
  ``enable()`` scope pushes the per-program catalog to the metrics
  sink on exit. Disabled cost: one module-global read per round.

Dynamic-trip caveat (measured, jax 0.4.37 / XLA CPU+TPU): cost analysis
of a ``while``/``fori_loop`` with a traced trip count charges the loop
body ONCE. For the bucket chunk programs that is exactly the useful
number -- the cost of one step across all ``client_chunk`` lanes (plus
the per-dispatch aggregation epilogue, which step-dominated chunks
amortize) -- so per-bucket executed FLOPs are
``program_flops / client_chunk * executed_lane_steps``.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Optional

#: Failure modes of AOT lowering / compilation / cost introspection that
#: must degrade to the analytic fallback, never crash a bench or a round
#: (cost_analysis is not part of jax's stable API surface).
_COST_ERRORS = (TypeError, ValueError, RuntimeError, NotImplementedError,
                AttributeError, KeyError, IndexError, ImportError)


@dataclass(frozen=True)
class ProgramCost:
    """Cost of one compiled XLA program (the whole dispatch)."""

    flops: float
    bytes_accessed: float
    source: str = "xla"  # "xla" (cost model) | "analytic" (fallback)


def compiled_cost(compiled) -> Optional[ProgramCost]:
    """``ProgramCost`` from a ``jax.stages.Compiled``, or None when the
    backend exposes no usable cost analysis (older jax returns a list of
    per-executable dicts, newer a dict; both are handled)."""
    try:
        ca = compiled.cost_analysis()
    except _COST_ERRORS:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", -1.0) or -1.0)
    if flops <= 0:
        return None
    return ProgramCost(flops=flops,
                       bytes_accessed=float(ca.get("bytes accessed", 0.0)
                                            or 0.0),
                       source="xla")


def program_cost(jitted_fn, *args, **kwargs) -> Optional[ProgramCost]:
    """Cost-analyze ``jitted_fn`` at these arg shapes via AOT
    ``lower().compile()``. Args may be concrete arrays or
    ``jax.ShapeDtypeStruct`` templates (nothing executes either way).

    The AOT compile does NOT populate the jit dispatch cache (pinned in
    tests -- ``compiled_shapes()``-style cache counts stay honest), but
    it IS a real XLA compile: callers cache per shape (see
    :class:`CostModel`) and the persistent compilation cache dedupes it
    against the dispatch-path compile on TPU-scale programs. Returns
    None on any failure -- callers fall back to their analytic number.
    """
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
    except _COST_ERRORS as e:
        logging.info("costmodel: lowering failed (%s: %s) -- falling back "
                     "to analytic FLOPs", type(e).__name__, e)
        return None
    return compiled_cost(compiled)


def train_step_cost(spec, cfg, batch) -> Optional[ProgramCost]:
    """Cost of ONE local training step for ``spec``/``cfg`` at ``batch``
    shapes -- the exact step the engine's trip loop runs: the spec's
    augmentation (when present), ``value_and_grad`` of the loss, and the
    optimizer update (optimizer state initialized in-program, as every
    client update does).

    ``batch``: ``{"x", "y", "mask"}`` of concrete arrays or
    ``jax.ShapeDtypeStruct``; model/optimizer state shapes are derived
    with ``jax.eval_shape`` so nothing ever touches a device. Divide
    ``flops`` by the batch size for per-sample train FLOPs.
    """
    import jax
    import optax

    # lazy: costmodel must stay importable without pulling the engine in
    # (engine imports get_cost_model from here at module top)
    from fedml_tpu.parallel.engine import make_optimizer

    try:
        optimizer = make_optimizer(cfg)

        def step(state, batch, rng):
            params = state["params"]
            rest = {k: v for k, v in state.items() if k != "params"}
            opt_state = optimizer.init(params)
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(rng, 13))

            def loss_wrapper(p):
                s = dict(rest)
                s["params"] = p
                return spec.loss_fn(s, batch, rng, True)

            (_, (new_state, metrics)), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(params)
            updates, _ = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), metrics

        state_shapes = jax.eval_shape(
            lambda: spec.init_fn(jax.random.PRNGKey(0)))
        rng_shape = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    except _COST_ERRORS as e:
        logging.info("costmodel: train-step construction failed (%s: %s)",
                     type(e).__name__, e)
        return None
    return program_cost(jax.jit(step), state_shapes, batch, rng_shape)


class CostModel:
    """Per-program cost catalog, armed via :func:`set_cost_model`.

    Instrumentation points (the bucketed stream runner, bench) call
    :meth:`note` once per distinct program they attribute; :meth:`record`
    renders the catalog as a metrics-record fragment
    (``cost/<name>_flops`` / ``_bytes``) that the ``enable()`` scope
    pushes to the metrics sink on exit. Thread-safe; a None cost is
    remembered too, so a backend without cost analysis is probed once,
    not once per round.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.programs = {}  # name -> ProgramCost | None

    def note(self, name, cost: Optional[ProgramCost]):
        with self._lock:
            self.programs.setdefault(name, cost)
        return cost

    def known(self, name) -> bool:
        with self._lock:
            return name in self.programs

    def get(self, name) -> Optional[ProgramCost]:
        with self._lock:
            return self.programs.get(name)

    def record(self, prefix="cost/") -> dict:
        with self._lock:
            out = {prefix + "programs": len(self.programs)}
            for name, pc in sorted(self.programs.items()):
                if pc is None:
                    out[prefix + name + "_flops"] = None
                else:
                    out[prefix + name + "_flops"] = pc.flops
                    out[prefix + name + "_bytes"] = pc.bytes_accessed
        return out


_cost_model = None


def get_cost_model():
    """The process-wide cost model, or None when attribution is off --
    instrumentation points guard with ``if cm is not None``."""
    return _cost_model


def set_cost_model(cm):
    global _cost_model
    prev = _cost_model
    _cost_model = cm
    return prev


__all__ = ["ProgramCost", "compiled_cost", "program_cost",
           "train_step_cost", "CostModel", "get_cost_model",
           "set_cost_model"]
