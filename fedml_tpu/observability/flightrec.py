"""Control-plane flight recorder: a bounded ring of events, dumped on death.

A flaky chaos failure ("the round hung once at 2 a.m.") is only debuggable
if the control plane's last N events survive the crash. The recorder keeps
a thread-safe ring buffer of structured events -- message send/recv with
type+rank+bytes, RoundController decisions, retry/backoff attempts,
lock-audit violations -- and snapshots it to
``<out_dir>/flightrec_<reason>.jsonl`` when something dies:

- ``peer_lost``: a transport synthesized ``MSG_TYPE_PEER_LOST`` (TCP
  EOF-without-GOODBYE, exhausted retry budget, local-network abort);
- ``abandoned_round``: the RoundController resolved an attempt below
  quorum;
- ``crash``: an unhandled exception reached the interpreter's top level
  (the ``enable()`` scope chains ``sys.excepthook`` /
  ``threading.excepthook`` while active).

Dumps are deduplicated per reason per recorder (the first death is the
interesting one; repeats append ``_2``, ``_3`` ... up to ``max_dumps``)
and each line is self-describing JSON, so a post-mortem is ``jq`` away.

Recording cost when enabled is one dict + deque append under a lock per
control-plane event (tens per round); when disabled the instrumentation
points read one module global and branch away.
"""

from __future__ import annotations

import json
import os
import threading
import time


class FlightRecorder:
    """Bounded ring of control-plane events.

    Args:
      out_dir: where dumps land (created on first dump).
      capacity: ring size in events (oldest evicted first).
      max_dumps: total dump-file cap per recorder (a crash loop must not
        fill the disk with identical post-mortems).
    """

    def __init__(self, out_dir=".", capacity=4096, max_dumps=8):
        from collections import deque
        self.out_dir = out_dir
        self._buf = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self.max_dumps = int(max_dumps)
        self.dumps = []          # paths written, in order
        self._reason_counts = {}

    def record(self, kind, **fields):
        """Append one event. ``fields`` must be JSON-serializable scalars
        (arrays and pytrees do not belong in a black box)."""
        evt = {"t": time.time(), "kind": str(kind),
               "thread": threading.current_thread().name}
        evt.update(fields)
        with self._lock:
            self._seq += 1
            evt["seq"] = self._seq
            self._buf.append(evt)
        return evt

    def snapshot(self):
        with self._lock:
            return list(self._buf)

    def dump(self, reason, extra=None):
        """Write the ring to ``flightrec_<reason>.jsonl``; returns the
        path (None once ``max_dumps`` is reached). The triggering context
        can attach an ``extra`` event appended after the ring."""
        reason = str(reason).replace(os.sep, "_")
        with self._lock:
            if len(self.dumps) >= self.max_dumps:
                return None
            n = self._reason_counts.get(reason, 0) + 1
            self._reason_counts[reason] = n
            name = (f"flightrec_{reason}.jsonl" if n == 1
                    else f"flightrec_{reason}_{n}.jsonl")
            events = list(self._buf)
            # path building + file I/O stay OUTSIDE the lock (record()
            # callers on hot paths must never wait on the filesystem)
            path = self.out_dir + os.sep + name
            self.dumps.append(path)
        os.makedirs(self.out_dir, exist_ok=True)
        with open(path, "w") as f:
            for evt in events:
                f.write(json.dumps(evt, default=str) + "\n")
            if extra:
                f.write(json.dumps({"t": time.time(), "kind": "dump_info",
                                    **extra}, default=str) + "\n")
        return path


_recorder = None


def get_flight_recorder():
    """The process-wide recorder, or None when off -- instrumentation
    points guard with ``if fr is not None``."""
    return _recorder


def set_flight_recorder(recorder):
    global _recorder
    prev = _recorder
    _recorder = recorder
    return prev


__all__ = ["FlightRecorder", "get_flight_recorder", "set_flight_recorder"]
