"""fedtrace: round tracing, a unified metrics registry, a flight recorder.

The observability layer the scale-out arc reports against (see
docs/OBSERVABILITY.md). Three composable pieces, one switchboard:

- :mod:`~fedml_tpu.observability.tracing`: Dapper-style spans over the
  round lifecycle, propagated across ranks in the message envelope's
  ``__trace__`` control field; Chrome-trace + JSONL export.
- :mod:`~fedml_tpu.observability.registry`: counters/gauges/histograms
  with labels; per-round snapshots into ``metrics.jsonl`` records and a
  Prometheus text dump at exit.
- :mod:`~fedml_tpu.observability.flightrec`: a bounded ring of
  control-plane events dumped to ``flightrec_<reason>.jsonl`` on
  PEER_LOST, abandoned rounds, and unhandled crashes.
- :mod:`~fedml_tpu.observability.jaxmon`: per-round compile count +
  duration via ``jax.monitoring``.

Everything defaults OFF: the module-level tracer is a no-op, the registry
and recorder globals are None, and every instrumentation point in the
engine/transports/FSMs guards on that -- a run without ``--trace`` /
``--flightrec`` executes no observability code beyond one global read per
event and produces bit-identical results. :func:`enable` flips the
switchboard for a scope and writes the artifacts on exit.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading

from fedml_tpu.observability.costmodel import (CostModel, get_cost_model,
                                               set_cost_model)
from fedml_tpu.observability.flightrec import (FlightRecorder,
                                               get_flight_recorder,
                                               set_flight_recorder)
from fedml_tpu.observability.perfmon import (PerfMonitor, StatusWriter,
                                             get_perf_monitor,
                                             set_perf_monitor)
from fedml_tpu.observability.registry import (MetricsRegistry, get_registry,
                                              set_registry)
from fedml_tpu.observability.tracing import (NOOP_TRACER, NoopTracer, Span,
                                             SpanContext, TRACE_KEY, Tracer,
                                             get_tracer, set_tracer)


def add_observability_args(parser):
    """``--trace/--trace_dir/--flightrec`` for the experiment mains
    (wired through ``experiments/common.add_base_args``)."""
    parser.add_argument(
        "--trace", type=int, default=0,
        help="structured span tracing of the round lifecycle "
             "(fedml_tpu.observability): cohort-select/broadcast/"
             "local-train/report/aggregate/eval spans, stitched across "
             "ranks via trace ids in the message envelope; exports "
             "trace.json (Perfetto/chrome://tracing) + spans.jsonl to "
             "--trace_dir and arms the per-round compile-event watcher")
    parser.add_argument(
        "--trace_dir", type=str, default=None,
        help="span export directory (default: --run_dir, else '.')")
    parser.add_argument(
        "--flightrec", type=int, default=0,
        help="control-plane flight recorder: bounded ring of "
             "send/recv/decision/retry events, dumped to "
             "flightrec_<reason>.jsonl on PEER_LOST, abandoned rounds, "
             "and unhandled crashes")
    parser.add_argument(
        "--perfmon", type=int, default=0,
        help="runtime perf/health monitor (observability/perfmon.py): "
             "round/step/staleness/buffer-depth/report-latency "
             "histograms into the metrics registry, a rolling "
             "fed_rounds_per_hour gauge, and periodic status.json "
             "health snapshots (--status_path)")
    parser.add_argument(
        "--status_path", type=str, default=None,
        help="status.json path for --perfmon health snapshots "
             "(default: <run_dir>/status.json when --run_dir is set)")
    parser.add_argument(
        "--xprof_round", type=int, default=None,
        help="with --perfmon: capture a programmatic jax.profiler trace "
             "of exactly round N into --xprof_dir (no-op when the "
             "profiler is unavailable; fires at most once)")
    parser.add_argument(
        "--xprof_dir", type=str, default=None,
        help="jax.profiler output dir for --xprof_round "
             "(default: --run_dir, else '.')")
    parser.add_argument(
        "--costmodel", type=int, default=0,
        help="XLA cost-model performance attribution "
             "(observability/costmodel.py): per-compiled-program "
             "FLOPs/bytes from cost_analysis(); the bucketed streaming "
             "rounds additionally report per-bucket-shape FLOPs and "
             "FLOP-weighted padding waste")
    return parser


@contextlib.contextmanager
def enable(trace=False, trace_dir=None, flightrec=False, flightrec_dir=None,
           registry=True, compile_events=None, metrics_logger=None,
           flight_capacity=4096, perfmon=False, status_path=None,
           xprof_dir=None, xprof_round=None, cost_model=False):
    """Arm the observability switchboard for a scope.

    Yields an object with ``tracer`` / ``registry`` / ``recorder`` /
    ``compile_watcher`` / ``monitor`` / ``cost_model`` attributes (None
    for the pieces left off). On exit: exports ``trace.json`` +
    ``spans.jsonl`` into ``trace_dir``, dumps the registry to
    ``metrics.prom`` (in ``flightrec_dir`` or ``trace_dir`` when either
    is set), pushes the compile / perf-monitor / cost-model reports to
    ``metrics_logger``, forces a final ``status.json`` write, and
    restores the previous globals (scopes nest).

    ``compile_events`` defaults to ``trace`` -- the watcher needs jax, so
    a flight-recorder-only scope stays jax-free. ``perfmon`` arms the
    registry too (its histograms need a sink); ``status_path`` defaults
    to ``<flightrec_dir or trace_dir>/status.json`` when perfmon is on
    and either dir is set.
    """
    state = _Scope()
    prev_tracer = prev_reg = prev_fr = prev_mon = prev_cm = None
    hooks = None
    if compile_events is None:
        compile_events = bool(trace)
    # the compile watcher is the ONLY fallible setup step (it imports
    # jax and registers a monitoring listener): arm it FIRST, before any
    # global is installed, so a setup failure cannot leak a tracer/
    # registry/recorder (or chained excepthooks) past this function --
    # everything below is plain-Python construction that cannot raise
    # (PerfMonitor/CostModel only touch jax lazily, inside a round)
    if compile_events:
        from fedml_tpu.observability.jaxmon import watch_compiles
        state._watch_cm = watch_compiles()
        state.compile_watcher = state._watch_cm.__enter__()
    if trace:
        state.tracer = Tracer()
        prev_tracer = set_tracer(state.tracer)
    if registry and (trace or flightrec or perfmon):
        state.registry = MetricsRegistry()
        prev_reg = set_registry(state.registry)
    if flightrec:
        state.recorder = FlightRecorder(
            out_dir=flightrec_dir or trace_dir or ".",
            capacity=flight_capacity)
        prev_fr = set_flight_recorder(state.recorder)
        hooks = _install_crash_hooks(state.recorder)
    if perfmon:
        out_dir = flightrec_dir or trace_dir
        if status_path is None and out_dir is not None:
            status_path = os.path.join(out_dir, "status.json")
        state.monitor = PerfMonitor(status_path=status_path,
                                    xprof_dir=xprof_dir or out_dir,
                                    xprof_round=xprof_round)
        prev_mon = set_perf_monitor(state.monitor)
    if cost_model:
        state.cost_model = CostModel()
        prev_cm = set_cost_model(state.cost_model)
    try:
        yield state
    finally:
        if state.compile_watcher is not None:
            state._watch_cm.__exit__(None, None, None)
            report = state.compile_watcher.report()
            logging.info("compile watch: %s", report)
            if metrics_logger is not None:
                metrics_logger(report)
        if state.cost_model is not None:
            set_cost_model(prev_cm)
            if metrics_logger is not None and state.cost_model.programs:
                metrics_logger(state.cost_model.record())
        if state.monitor is not None:
            set_perf_monitor(prev_mon)
            state.monitor.status_update(force=True, final=True)
            if state.monitor.status is not None:
                state.status_path = state.monitor.status.path
            if metrics_logger is not None and state.monitor.rounds:
                metrics_logger(state.monitor.record())
        if state.recorder is not None:
            _uninstall_crash_hooks(hooks)
            set_flight_recorder(prev_fr)
        if state.registry is not None:
            set_registry(prev_reg)
            out_dir = flightrec_dir or trace_dir
            if out_dir is not None:
                os.makedirs(out_dir, exist_ok=True)
                state.prom_path = state.registry.dump_prometheus(
                    os.path.join(out_dir, "metrics.prom"))
        if state.tracer is not None:
            set_tracer(prev_tracer)
            if trace_dir is not None:
                os.makedirs(trace_dir, exist_ok=True)
                state.chrome_path = state.tracer.export_chrome(
                    os.path.join(trace_dir, "trace.json"))
                state.spans_path = state.tracer.export_jsonl(
                    os.path.join(trace_dir, "spans.jsonl"))
                logging.info(
                    "fedtrace: %d spans -> %s (open in Perfetto / "
                    "chrome://tracing)", len(state.tracer.finished_spans()),
                    state.chrome_path)


class _Scope:
    """What :func:`enable` yields; also records artifact paths on exit."""

    def __init__(self):
        self.tracer = None
        self.registry = None
        self.recorder = None
        self.compile_watcher = None
        self.monitor = None
        self.cost_model = None
        self.chrome_path = None
        self.spans_path = None
        self.prom_path = None
        self.status_path = None
        self._watch_cm = None


def _install_crash_hooks(recorder):
    """Chain sys/threading excepthooks: an unhandled crash dumps the ring
    before the interpreter's default handling runs."""
    prev_sys = sys.excepthook
    prev_thr = threading.excepthook

    def on_crash(exc_type, exc, tb):
        try:
            recorder.record("crash", error=f"{exc_type.__name__}: {exc}")
            recorder.dump("crash", extra={"error": repr(exc)})
        except OSError:  # the disk is gone too: still run default handling
            pass
        prev_sys(exc_type, exc, tb)

    def on_thread_crash(args):
        try:
            recorder.record("crash", thread_name=getattr(
                args.thread, "name", "?"),
                error=f"{args.exc_type.__name__}: {args.exc_value}")
            recorder.dump("crash", extra={"error": repr(args.exc_value)})
        except OSError:
            pass
        prev_thr(args)

    sys.excepthook = on_crash
    threading.excepthook = on_thread_crash
    return (prev_sys, prev_thr, on_crash, on_thread_crash)


def _uninstall_crash_hooks(hooks):
    if hooks is None:
        return
    prev_sys, prev_thr, on_crash, on_thread_crash = hooks
    # only unwind our own frame: someone may have chained on top of us
    if sys.excepthook is on_crash:
        sys.excepthook = prev_sys
    if threading.excepthook is on_thread_crash:
        threading.excepthook = prev_thr


__all__ = ["Tracer", "NoopTracer", "NOOP_TRACER", "Span", "SpanContext",
           "TRACE_KEY", "get_tracer", "set_tracer",
           "MetricsRegistry", "get_registry", "set_registry",
           "FlightRecorder", "get_flight_recorder", "set_flight_recorder",
           "PerfMonitor", "StatusWriter", "get_perf_monitor",
           "set_perf_monitor",
           "CostModel", "get_cost_model", "set_cost_model",
           "add_observability_args", "enable"]
