"""Compile-event watcher: the first honest measurement of compile latency.

ROADMAP names 155-193 s per-config compiles as a cost center, but until
now nothing *measured* them per round -- the runtime auditor counts trace
events for its retrace gate, while durations were eyeballed from logs.
This listener subscribes to ``jax.monitoring``'s duration events
(jaxpr trace + backend compile) and buckets **count and wall seconds per
federated round** at the same ``end_of_round_sync`` interception point
the auditor uses, feeding:

- the metrics registry (``jax_compiles_total``, ``jax_traces_total``
  counters; ``jax_compile_seconds`` histogram) when one is enabled;
- per-round lists in :meth:`CompileWatcher.report` (mirrored into the
  final metrics record by the ``enable()`` scope).

Unlike the auditor this is pure measurement -- no transfer guard, no
gates -- so it can stay on for every traced run.
"""

from __future__ import annotations

import contextlib
import threading

#: jax.monitoring event names (same stable strings the runtime auditor
#: pins; see fedml_tpu.analysis.runtime).
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
#: persistent-compilation-cache outcomes. Measured (jax 0.4.37): a cache
#: HIT still fires COMPILE_EVENT -- its duration is the cache-load time,
#: not an XLA compile -- so the warm-restart gate is "zero cache MISSES"
#: (every compile served from the warmed cache), not "zero compile
#: events" (docs/OBSERVABILITY.md, fedwarm).
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_current = None


def current_watcher():
    return _current


class CompileWatcher:
    """Counts jax trace/compile events and their durations, bucketed per
    round by :meth:`mark_round` (wired through ``end_of_round_sync``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._compiles = 0
        self._compile_s = 0.0
        self._traces = 0
        self.rounds = 0
        self.compiles_per_round = []
        self.compile_seconds_per_round = []
        self.traces_per_round = []
        self.total_compiles = 0
        self.total_compile_seconds = 0.0
        self.total_traces = 0
        # persistent-compilation-cache outcomes (plain jax.monitoring
        # events): a warmed cache turns every compile into a HIT whose
        # COMPILE_EVENT duration is the deserialization time -- the
        # warm-restart gate asserts cache_misses == 0, since compile
        # COUNT stays nonzero even when nothing XLA-compiles
        self.cache_hits = 0
        self.cache_misses = 0

    def _on_event(self, event, duration_secs, **kwargs):
        if not self._active:
            return
        from fedml_tpu.observability.registry import get_registry
        reg = get_registry()
        with self._lock:
            if event == COMPILE_EVENT:
                self._compiles += 1
                self._compile_s += float(duration_secs)
                self.total_compiles += 1
                self.total_compile_seconds += float(duration_secs)
            elif event == TRACE_EVENT:
                self._traces += 1
                self.total_traces += 1
            else:
                return
        if reg is not None:
            if event == COMPILE_EVENT:
                reg.inc("jax_compiles_total",
                        help="XLA backend compiles observed")
                reg.observe("jax_compile_seconds", float(duration_secs),
                            help="XLA backend compile wall seconds")
            else:
                reg.inc("jax_traces_total",
                        help="jaxpr traces observed")

    def _on_plain_event(self, event, **kwargs):
        if not self._active:
            return
        if event == CACHE_HIT_EVENT:
            with self._lock:
                self.cache_hits += 1
        elif event == CACHE_MISS_EVENT:
            with self._lock:
                self.cache_misses += 1

    def mark_round(self):
        """Close the current round's bucket (round 0 holds warm-up)."""
        with self._lock:
            self.compiles_per_round.append(self._compiles)
            self.compile_seconds_per_round.append(round(self._compile_s, 4))
            self.traces_per_round.append(self._traces)
            self._compiles = 0
            self._compile_s = 0.0
            self._traces = 0
            self.rounds += 1

    def report(self):
        with self._lock:
            return {
                "compile/rounds": self.rounds,
                "compile/compiles_per_round": list(self.compiles_per_round),
                "compile/seconds_per_round":
                    list(self.compile_seconds_per_round),
                "compile/traces_per_round": list(self.traces_per_round),
                "compile/total_compiles": self.total_compiles,
                "compile/total_seconds":
                    round(self.total_compile_seconds, 4),
                "compile/total_traces": self.total_traces,
                "compile/cache_hits": self.cache_hits,
                "compile/cache_misses": self.cache_misses,
            }

    def record_fields(self) -> dict:
        """Flat compile-cost fields for a bench record / ledger entry
        (count + wall seconds + persistent-cache outcomes; the per-round
        lists stay in :meth:`report`)."""
        with self._lock:
            return {"compile_count": self.total_compiles,
                    "compile_seconds":
                        round(self.total_compile_seconds, 4),
                    "compile_cache_hits": self.cache_hits,
                    "compile_cache_misses": self.cache_misses}

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        from jax import monitoring
        self._active = True
        monitoring.register_event_duration_secs_listener(self._on_event)
        try:  # plain-event listener: the cache-outcome feed (older jax
            # may lack it; durations still work without)
            monitoring.register_event_listener(self._on_plain_event)
            self._plain_registered = True
        except AttributeError:
            self._plain_registered = False
        return self

    def stop(self):
        self._active = False
        # jax only exposes clear-all publicly; reuse the auditor's
        # best-effort dereg (leaving the inert listener on API drift)
        from fedml_tpu.analysis.runtime import _unregister
        _unregister(self._on_event)
        if getattr(self, "_plain_registered", False):
            try:
                from jax._src import monitoring as _mon
                _mon._unregister_event_listener_by_callback(
                    self._on_plain_event)
            except (ImportError, AttributeError, AssertionError):
                pass  # inert listener stays registered on API drift


@contextlib.contextmanager
def watch_compiles():
    """Arm a :class:`CompileWatcher` for the block; yields it. The round
    loops' ``end_of_round_sync`` calls :meth:`CompileWatcher.mark_round`
    on the current watcher, so per-round buckets need no extra wiring."""
    global _current
    watcher = CompileWatcher().start()
    prev, _current = _current, watcher
    try:
        yield watcher
    finally:
        _current = prev
        watcher.stop()


__all__ = ["CompileWatcher", "watch_compiles", "current_watcher",
           "TRACE_EVENT", "COMPILE_EVENT", "CACHE_HIT_EVENT",
           "CACHE_MISS_EVENT"]
