"""Structured span tracing for the federated round lifecycle ("fedtrace").

Dapper-style distributed tracing (Sigelman et al., 2010) scaled down to
this control plane: every span carries a ``trace_id`` shared by the whole
round tree and a ``parent_id`` naming the span it hangs under. Inside one
process, parentage flows through a thread-local context stack; across
ranks it rides the message envelope -- :meth:`Tracer.inject` writes a
``{"trace_id", "span_id"}`` dict under the reserved ``__trace__`` control
field (JSON header of the binary codec, so every transport carries it for
free) and the manager dispatch loop re-establishes it around handlers via
:meth:`Tracer.remote_context`. The result: a client rank's ``local-train``
span stitches under the server's ``round`` span into one tree, viewable in
Perfetto / ``chrome://tracing`` via :meth:`Tracer.export_chrome`.

Disabled-path contract: the module-level tracer defaults to
:data:`NOOP_TRACER`, whose spans are a single shared no-op context
manager and whose ``inject`` leaves messages untouched -- a run without
``--trace`` sends bit-identical frames and executes no tracing code
beyond one global read per instrumentation point.

Stdlib-only at import time (the transports must stay importable without
jax); ``jax.profiler`` integration is opt-in and imported lazily.
"""

from __future__ import annotations

import json
import os
import threading
import time


#: Reserved message control field carrying the trace context on the wire.
TRACE_KEY = "__trace__"


def _new_id(nbytes=8):
    return os.urandom(nbytes).hex()


class SpanContext:
    """The propagatable half of a span: what children need to stitch."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def as_dict(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d):
        try:
            return cls(str(d["trace_id"]), str(d["span_id"]))
        except (TypeError, KeyError):
            return None


class Span:
    """One timed phase. Created by :meth:`Tracer.start_span` (detached --
    for cross-thread begin/end like the server's per-attempt round span)
    or :meth:`Tracer.span` (context manager, thread-local parentage)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t0", "t1", "thread", "_tracer")

    def __init__(self, tracer, name, trace_id, parent_id, attrs):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self.t0 = tracer._now()
        self.t1 = None
        self.thread = threading.current_thread().name

    @property
    def context(self):
        return SpanContext(self.trace_id, self.span_id)

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def end(self):
        """Idempotent: a span double-ended by a racing path records once
        (the check-and-set runs under the tracer's lock -- two genuinely
        concurrent end() calls record exactly one span)."""
        self._tracer._finish(self, self._tracer._now())

    def as_dict(self):
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "ts": self.t0, "dur": (self.t1 or self.t0) - self.t0,
                "thread": self.thread, "attrs": self.attrs}


class _SpanScope:
    """Context manager pairing a span with the thread-local stack."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer, span):
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        self._tracer._push(self.span.context)
        return self.span

    def __exit__(self, *exc):
        self._tracer._pop()
        self.span.end()
        return False


class Tracer:
    """Collects spans; thread-safe; exports Chrome trace-event JSON + JSONL.

    Args:
      max_spans: retention bound -- the oldest spans are dropped beyond it
        (a multi-hour run must not grow host memory without bound). The
        drop count is reported in the Chrome export's metadata.
    """

    enabled = True

    def __init__(self, max_spans=200_000):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._spans = []
        self._dropped = 0
        self._max = int(max_spans)
        #: epoch anchor: span timestamps are epoch-based microseconds so
        #: traces from different processes of one job align in Perfetto
        self._t0_epoch = time.time()
        self._t0_perf = time.perf_counter()

    def _now(self):
        # monotonic progression, epoch-anchored (us)
        return (self._t0_epoch
                + (time.perf_counter() - self._t0_perf)) * 1e6

    # -- thread-local context stack ---------------------------------------
    def _stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _push(self, ctx):
        self._stack().append(ctx)

    def _pop(self):
        stack = self._stack()
        if stack:
            stack.pop()

    def current(self):
        """The innermost active context on this thread (or None)."""
        stack = self._stack()
        return stack[-1] if stack else None

    def remote_context(self, ctx):
        """Adopt a foreign :class:`SpanContext` (extracted from a message)
        as this thread's current parent for the ``with`` block -- the
        receive-side half of cross-rank stitching."""
        return _RemoteScope(self, ctx)

    # -- span creation -----------------------------------------------------
    def start_span(self, name, parent=None, root=False, **attrs):
        """Detached span: NOT pushed on the thread-local stack, so it can
        be ended from another thread (the FSM round span's lifecycle).
        ``parent`` is a :class:`SpanContext`; None falls back to the
        calling thread's current context; ``root=True`` forces a fresh
        trace even when a context is active (the server's per-attempt
        round spans are roots regardless of which handler thread opened
        them)."""
        ctx = None if root else (
            parent if parent is not None else self.current())
        if ctx is not None:
            return Span(self, name, ctx.trace_id, ctx.span_id, attrs)
        return Span(self, name, _new_id(), None, attrs)

    def span(self, name, parent=None, root=False, **attrs):
        """Context-managed span parented on this thread's current context
        (or ``parent`` when given); children opened inside see it."""
        return _SpanScope(self, self.start_span(name, parent=parent,
                                                root=root, **attrs))

    def _finish(self, span, t1):
        with self._lock:
            if span.t1 is not None:
                return  # racing double-end: first one won
            span.t1 = t1
            if len(self._spans) >= self._max:
                # drop oldest half in one amortized cut (per-append pops
                # would be quadratic)
                self._spans = self._spans[len(self._spans) // 2:]
                self._dropped += self._max - len(self._spans)
            self._spans.append(span)

    # -- wire propagation --------------------------------------------------
    def inject(self, msg, ctx=None):
        """Attach ``ctx`` (default: this thread's current context) to a
        :class:`~fedml_tpu.core.message.Message` under ``__trace__``; the
        binary codec carries it as a JSON control field."""
        ctx = ctx if ctx is not None else self.current()
        if ctx is not None:
            msg.add(TRACE_KEY, ctx.as_dict())

    @staticmethod
    def extract(msg):
        """The receive-side inverse: a :class:`SpanContext` or None."""
        d = msg.get(TRACE_KEY)
        return SpanContext.from_dict(d) if isinstance(d, dict) else None

    # -- introspection / export --------------------------------------------
    def finished_spans(self):
        with self._lock:
            return list(self._spans)

    def durations_by_name(self):
        """``{span name: [durations in seconds]}`` -- the bench's
        per-phase attribution feed."""
        out = {}
        for s in self.finished_spans():
            out.setdefault(s.name, []).append(
                ((s.t1 or s.t0) - s.t0) / 1e6)
        return out

    def export_jsonl(self, path):
        """One JSON line per span (trace/span/parent ids, ts/dur in us)."""
        with open(path, "w") as f:
            for s in self.finished_spans():
                f.write(json.dumps(s.as_dict()) + "\n")
        return path

    def export_chrome(self, path):
        """Chrome trace-event JSON (load in Perfetto / chrome://tracing).

        Every finished span becomes a balanced B/E pair; pid groups by
        span thread name is not enough for cross-rank trees, so the trace
        and span ids ride in ``args`` and ``rank`` attrs (when present)
        name the track."""
        events = []
        threads = {}
        for s in self.finished_spans():
            tid = threads.setdefault(s.thread, len(threads))
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            args.update({str(k): _jsonable(v) for k, v in s.attrs.items()})
            common = {"name": s.name, "cat": "fed", "pid": 0, "tid": tid}
            events.append({"ph": "B", "ts": s.t0, "args": args, **common})
            events.append({"ph": "E", "ts": s.t1 or s.t0, **common})
        meta = [{"ph": "M", "pid": 0, "tid": tid, "name": "thread_name",
                 "args": {"name": tname}}
                for tname, tid in sorted(threads.items(), key=lambda kv: kv[1])]
        doc = {"traceEvents": meta + events, "displayTimeUnit": "ms",
               "otherData": {"dropped_spans": self._dropped}}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


class _RemoteScope:
    __slots__ = ("_tracer", "_ctx")

    def __init__(self, tracer, ctx):
        self._tracer = tracer
        self._ctx = ctx

    def __enter__(self):
        self._tracer._push(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        self._tracer._pop()
        return False


def _jsonable(v):
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    return str(v)


# -- the no-op tracer ----------------------------------------------------

class _NoopScope:
    """Shared, reusable no-op context manager (also quacks like a Span)."""

    __slots__ = ()
    name = trace_id = span_id = parent_id = None
    context = None
    span = None  # _SpanScope surface parity

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def end(self):
        return None


_NOOP_SCOPE = _NoopScope()
_NoopScope.span = _NOOP_SCOPE  # `with t.span(..) as s:` yields the noop


class NoopTracer:
    """Zero-cost stand-in when tracing is off: every method returns a
    shared inert object; ``inject`` leaves the message untouched, so
    disabled runs put bit-identical frames on the wire."""

    enabled = False

    def span(self, name, parent=None, root=False, **attrs):
        return _NOOP_SCOPE

    def start_span(self, name, parent=None, root=False, **attrs):
        return _NOOP_SCOPE

    def remote_context(self, ctx):
        return _NOOP_SCOPE

    def current(self):
        return None

    def inject(self, msg, ctx=None):
        return None

    @staticmethod
    def extract(msg):
        return None

    def finished_spans(self):
        return []

    def durations_by_name(self):
        return {}


NOOP_TRACER = NoopTracer()
_tracer = NOOP_TRACER


def get_tracer():
    """The process-wide tracer (default: :data:`NOOP_TRACER`)."""
    return _tracer


def set_tracer(tracer):
    """Install ``tracer`` (None restores the no-op); returns the previous
    one so scopes can nest."""
    global _tracer
    prev = _tracer
    _tracer = tracer if tracer is not None else NOOP_TRACER
    return prev


__all__ = ["TRACE_KEY", "SpanContext", "Span", "Tracer", "NoopTracer",
           "NOOP_TRACER", "get_tracer", "set_tracer"]
