"""Runtime performance monitor: pace/health telemetry for long runs.

PR 9's async path exposed buffer depth and staleness as point gauges;
production FL systems live on *distributions* and *pace* (Bonawitz et
al., MLSys 2019 section 3: pace steering reads rounds/hour and straggler
tails, not last-value gauges). This module adds, behind the same
default-OFF switchboard as the rest of ``fedml_tpu.observability``:

- :class:`PerfMonitor` -- feeds the existing metrics registry with
  HISTOGRAMS (per-round wall seconds, per-step seconds, client update
  staleness, buffer depth at fold, per-report latency whose upper
  buckets are the straggler tail) plus a rolling ``fed_rounds_per_hour``
  gauge over a bounded window; owns the optional status writer and the
  ``--xprof_round`` capture window. Disabled cost: one module-global
  read per instrumentation point (``get_perf_monitor() is None``).
- :class:`StatusWriter` -- a throttled, atomic (`tmp` + ``os.replace``)
  ``status.json`` snapshot so an operator (or a watchdog) can read a
  distributed server's live health -- round/attempt, outcome counts,
  alive ranks, buffer depth, last flush reason -- without attaching to
  logs. Decision points write ``force=True``; high-rate points (folds)
  are throttled to ``min_interval_s``.
- ``--xprof_round N`` -- a programmatic ``jax.profiler`` capture window
  around exactly round N (the XLA-level complement to fedtrace's host
  spans), no-op when the profiler is unavailable or busy.
- the **perf-regression ledger** -- ``append_ledger`` /
  ``check_regression``: every ``bench.py`` perf run appends its record
  to ``bench_results/ledger.jsonl``; ``bench.py --check-regress``
  compares the newest record against the median of its predecessors
  (same ``metric`` string) with a noise band and exits non-zero on
  regression. Gated both ways in ``scripts/ci.sh``.

Stdlib-only at import time (jax is touched only inside an armed xprof
window), so transports and hosts without an accelerator import this for
free.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque

from fedml_tpu.observability.registry import get_registry

#: Bucket layouts for the monitor's histograms: latency-flavored seconds
#: for round/report times, tighter sub-second edges for steps, small
#: integer edges for staleness/depth counts.
#:
#: The sub-1 s region is deliberately fine-grained (ISSUE 14 / ROADMAP
#: steering follow-up (b)): the pace controller's tail tracker reads
#: bucket UPPER EDGES as its p50/p90, so the old 0.1/0.25/0.5 ladder
#: quantized every sub-250 ms latency regime to the 0.25 edge and the
#: steered deadline could never track tighter. Roughly 1.4-2x edge
#: ratios below 1 s keep the tracker's resolution ~= its geometric rate
#: limit; the controller LAW is unchanged -- only its input resolution
#: (quantile-resolution test in tests/test_steering.py).
ROUND_BUCKETS = (0.01, 0.025, 0.05, 0.075, 0.1, 0.15, 0.25, 0.35, 0.5,
                 0.75, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                 300.0, 600.0)
STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0)
COUNT_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, 4096)


class StatusWriter:
    """Merged-field ``status.json`` snapshots, throttled and atomic.

    ``update(**fields)`` merges into the held snapshot and rewrites the
    file when ``force=True`` or ``min_interval_s`` has elapsed since the
    last write. The write is tmp-file + ``os.replace`` so a reader never
    observes a torn JSON document. Thread-safe (handler threads and the
    turnover thread both report)."""

    def __init__(self, path, min_interval_s=2.0):
        self.path = str(path)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._fields = {"status_version": 1}
        self._last_write = 0.0
        self.writes = 0

    def update(self, force=False, **fields):
        # the file commit happens UNDER the lock: two racing forced
        # updates must not os.replace() out of order and leave the file
        # holding the older snapshot. Writes are decision-rate (or
        # throttled), and this lock guards nothing else, so holding it
        # across one small local write is fine.
        with self._lock:
            self._fields.update(fields)
            now = time.time()
            if not force and now - self._last_write < self.min_interval_s:
                return None
            self._fields["updated_at"] = now
            snapshot = dict(self._fields)
            tmp = self.path + ".tmp"
            try:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(snapshot, f, indent=2, sort_keys=True,
                              default=str)
                os.replace(tmp, self.path)
            except OSError as e:  # health must never kill the run -- and
                # a failed write must not advance the throttle clock or
                # the write counter
                logging.warning("perfmon: status write to %s failed: %s",
                                self.path, e)
                return None
            self._last_write = now
            self.writes += 1
        return self.path


class PerfMonitor:
    """Overhead-bounded run-health monitor (see module docstring).

    Every ``observe_*`` is a bounded-deque append plus, when the metrics
    registry is armed, one histogram observation -- O(1) host work, no
    device touches, no effect on any computed value (the disabled-path
    bitwise A/B in tests/test_observability.py runs with the monitor
    armed on the enabled side)."""

    def __init__(self, status_path=None, xprof_dir=None, xprof_round=None,
                 window=128, status_interval_s=2.0):
        self.status = (StatusWriter(status_path,
                                    min_interval_s=status_interval_s)
                       if status_path else None)
        self.xprof_dir = xprof_dir
        self.xprof_round = (int(xprof_round)
                            if xprof_round is not None else None)
        self._lock = threading.Lock()
        self._round_ends = deque(maxlen=max(2, int(window)))
        self.rounds = 0
        self.reports = 0
        self._xprof_done = False

    # -- observations ------------------------------------------------------
    def observe_round(self, seconds, steps=None):
        """One federated round (or distributed round attempt) completed
        in ``seconds``; ``steps`` (true client-steps executed, when the
        caller knows them host-side) additionally feeds the per-step
        histogram and never forces a device sync to learn."""
        now = time.time()
        with self._lock:
            self._round_ends.append(now)
            self.rounds += 1
            rph = self._rph_locked()
        reg = get_registry()
        if reg is not None:
            reg.observe("fed_round_seconds", float(seconds),
                        buckets=ROUND_BUCKETS,
                        help="wall seconds per federated round")
            if steps:
                reg.observe("fed_step_seconds",
                            float(seconds) / max(int(steps), 1),
                            buckets=STEP_BUCKETS,
                            help="wall seconds per executed client step "
                                 "(round time / true steps)")
            if rph is not None:
                reg.set_gauge("fed_rounds_per_hour", rph,
                              help="rolling rounds/hour over the last "
                                   "window of rounds")
        return rph

    def _rph_locked(self):
        """THE rolling rounds/hour formula (callers hold ``_lock``):
        one definition feeds the gauge, ``rounds_per_hour()`` and
        ``record()`` so they can never drift apart."""
        if len(self._round_ends) < 2:
            return None
        span = self._round_ends[-1] - self._round_ends[0]
        if span <= 0:
            return None
        return round(3600.0 * (len(self._round_ends) - 1) / span, 2)

    def rounds_per_hour(self):
        """Current rolling rounds/hour (None until two observations) --
        the same value the ``fed_rounds_per_hour`` gauge holds, exposed
        so both distributed servers can put the live pace in their
        ``status.json`` snapshot on either paradigm (sync rounds and
        async flushes feed the one gauge)."""
        with self._lock:
            return self._rph_locked()

    def observe_report_latency(self, seconds):
        """Seconds from a round attempt's open to one client report --
        the distribution whose upper buckets ARE the straggler tail."""
        with self._lock:
            self.reports += 1
        reg = get_registry()
        if reg is not None:
            reg.observe("fed_report_latency_seconds", float(seconds),
                        buckets=ROUND_BUCKETS,
                        help="round-open to client report; upper buckets "
                             "are the straggler tail")

    def observe_fold(self, staleness, depth):
        """One async buffer fold: staleness + post-fold depth
        distributions (the histogram complement of the point gauges
        PR 9 ships on every fold)."""
        reg = get_registry()
        if reg is not None:
            reg.observe("fed_staleness_levels", int(staleness),
                        buckets=COUNT_BUCKETS,
                        help="staleness (server versions) distribution "
                             "of folded updates")
            reg.observe("fed_buffer_depth_levels", int(depth),
                        buckets=COUNT_BUCKETS,
                        help="buffer depth observed at each fold")

    # -- status ------------------------------------------------------------
    def status_update(self, force=False, **fields):
        if self.status is None:
            return None
        return self.status.update(force=force, **fields)

    # -- xprof window ------------------------------------------------------
    def xprof(self, round_idx):
        """Context manager: a ``jax.profiler`` trace of exactly round
        ``xprof_round`` written to ``xprof_dir``. Any other round -- and
        any profiler failure (unavailable backend, a trace already
        running) -- is a clean no-op; the capture fires at most once."""
        if (self.xprof_round is None or self._xprof_done
                or int(round_idx) != self.xprof_round):
            return contextlib.nullcontext()
        return self._xprof_capture(round_idx)

    @contextlib.contextmanager
    def _xprof_capture(self, round_idx):
        out_dir = self.xprof_dir or "."
        started = False
        try:
            import jax
            jax.profiler.start_trace(str(out_dir))
            started = True
        except (ImportError, RuntimeError, ValueError, OSError) as e:
            logging.warning("perfmon: --xprof_round %d capture unavailable "
                            "(%s: %s) -- continuing without it",
                            round_idx, type(e).__name__, e)
        self._xprof_done = True  # one shot, even if the start failed
        try:
            yield
        finally:
            if started:
                try:
                    import jax
                    jax.profiler.stop_trace()
                    logging.info("perfmon: xprof trace of round %d -> %s",
                                 round_idx, out_dir)
                except (ImportError, RuntimeError, ValueError, OSError) as e:
                    logging.warning("perfmon: xprof stop failed (%s: %s)",
                                    type(e).__name__, e)

    def record(self, prefix="perf/") -> dict:
        """Cumulative monitor summary for the metrics sink at scope
        exit."""
        with self._lock:
            out = {prefix + "rounds_observed": self.rounds,
                   prefix + "reports_observed": self.reports}
            rph = self._rph_locked()
            if rph is not None:
                out[prefix + "rounds_per_hour"] = rph
        if self.status is not None:
            out[prefix + "status_path"] = self.status.path
            out[prefix + "status_writes"] = self.status.writes
        return out


_monitor = None


def get_perf_monitor():
    """The process-wide monitor, or None when perf monitoring is off --
    instrumentation points guard with ``if mon is not None``."""
    return _monitor


def set_perf_monitor(monitor):
    global _monitor
    prev = _monitor
    _monitor = monitor
    return prev


# -- perf-regression ledger -------------------------------------------------

#: Default noise band for :func:`check_regression`: the newest record
#: regresses when its headline value drops below ``median * (1 - band)``
#: of its same-metric predecessors. 15% absorbs normal host jitter while
#: the CI fixture's injected 2x slowdown lands far outside it.
DEFAULT_REGRESS_BAND = 0.15


def append_ledger(record, path):
    """Append one bench record (dict) to the JSONL ledger at ``path``,
    stamped with the append time. Returns the path."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps({"ledger_ts": time.time(), **record},
                           sort_keys=True) + "\n")
    return path


def ledger_records(path):
    """All parseable records in the ledger, oldest first (unparseable
    lines are skipped with a warning, never fatal -- the ledger is
    append-only across tool versions)."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                logging.warning("ledger %s line %d unparseable -- skipped",
                                path, i + 1)
    return out


def check_regression(path, band=DEFAULT_REGRESS_BAND):
    """Compare each metric's newest record against the median of its
    predecessors (higher-is-better headline ``value``: rounds/hour,
    clients/sec, reports/sec, decode frames/sec).

    Baseline = all EARLIER records with the same ``metric`` string (a
    smoke record never judges a flagship run and vice versa), and EVERY
    distinct metric's latest record is judged -- a run that appends
    several rows (the soak bench writes reports/sec AND decode
    frames/sec) cannot shadow one metric's regression behind another's
    newer record. A fresh ledger -- no record at all, or no metric with
    a same-metric predecessor -- passes. Returns ``(ok, detail_dict)``;
    the CLI (``bench.py --check-regress``) prints the detail as one
    JSON line and exits non-zero when ``ok`` is False.
    """
    records = ledger_records(path)
    detail = {"check": "perf-regression", "ledger": path,
              "records": len(records), "band": band}
    if not records:
        detail.update({"fresh_ledger": True, "pass": True})
        return True, detail
    by_metric = {}        # metric -> ordered values (numeric), last rec
    for r in records:
        vals, _ = by_metric.setdefault(r.get("metric"), ([], None))
        if isinstance(r.get("value"), (int, float)):
            vals.append(r.get("value"))
        by_metric[r.get("metric")] = (vals, r)
    judged = []
    for metric, (vals, latest) in by_metric.items():
        value = latest.get("value")
        baseline = (vals[:-1] if isinstance(value, (int, float))
                    else vals)
        if not baseline:
            continue  # no same-metric predecessor: fresh for this metric
        ordered = sorted(baseline)
        n = len(ordered)
        median = (ordered[n // 2] if n % 2 else
                  0.5 * (ordered[n // 2 - 1] + ordered[n // 2]))
        threshold = median * (1.0 - band)
        ok = isinstance(value, (int, float)) and value >= threshold
        judged.append({"metric": metric, "latest_value": value,
                       "baseline_records": n, "baseline_median": median,
                       "threshold": round(threshold, 4), "pass": ok})
    if not judged:
        detail.update({"fresh_ledger": True, "pass": True})
        return True, detail
    ok = all(j["pass"] for j in judged)
    # top-level fields mirror the single-metric shape: the (first)
    # failing metric when red, the last-judged metric when green
    head = next((j for j in judged if not j["pass"]), judged[-1])
    detail.update({"fresh_ledger": False, **head, "pass": ok,
                   "metrics": judged})
    return ok, detail


__all__ = ["PerfMonitor", "StatusWriter", "get_perf_monitor",
           "set_perf_monitor", "append_ledger", "ledger_records",
           "check_regression", "DEFAULT_REGRESS_BAND", "ROUND_BUCKETS",
           "STEP_BUCKETS", "COUNT_BUCKETS"]
