"""Unified metrics registry: counters, gauges, histograms with labels.

One registry absorbs the counters previously scattered across the
transports (wire bytes, resends), the resilience layer (round outcomes,
retries, drops) and the runtime auditors (retrace/transfer totals), behind
three primitives:

- ``inc(name, value, **labels)``  -- monotonic counter
- ``set_gauge(name, value, **labels)`` -- last-value gauge
- ``observe(name, value, **labels)``   -- histogram (cumulative buckets)

Naming convention (documented in docs/OBSERVABILITY.md): snake_case,
unit-suffixed (``_total`` for counters, ``_seconds`` / ``_bytes`` for
sized values), labels for dimensions that fan out (``transport``,
``direction``, ``outcome``) -- Prometheus exposition rules, so
:meth:`MetricsRegistry.render_prometheus` is a straight dump into
``<run_dir>/metrics.prom``.

Per-round visibility: :meth:`snapshot_into` merges every series that
changed since the previous snapshot into a metrics record (prefix
``m/``), which :class:`~fedml_tpu.utils.metrics.MetricsLogger` calls on
each ``log()`` -- so round records in ``metrics.jsonl`` carry the wire /
resilience / compile counters that moved that round.

Thread-safe; stdlib-only; disabled-path cost is one module-global read
returning None at each instrumentation point.
"""

from __future__ import annotations

import math
import re
import threading

#: Default histogram buckets: latency-flavored seconds (also fine for
#: small counts); pass ``buckets=`` to ``observe`` for sized values.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0)

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _label_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key, extra=()):
    items = list(key) + list(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def _fmt_value(v):
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"  # repr() would render 'nan' -- grammar-invalid
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, bool):
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(int(v))


class _Hist:
    __slots__ = ("buckets", "counts", "total", "count")

    def __init__(self, buckets):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf
        self.total = 0.0
        self.count = 0

    def observe(self, v):
        v = float(v)
        self.total += v
        self.count += 1
        for i, le in enumerate(self.buckets):
            if v <= le:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class MetricsRegistry:
    """Label-aware counter/gauge/histogram store."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"type", "help", "series": {label_key: value|_Hist}}
        self._metrics = {}
        # snapshot_into change tracking: flat key -> last emitted value
        self._last_snapshot = {}

    def _series(self, name, kind, help_text):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r} (Prometheus "
                             "exposition: [a-zA-Z_:][a-zA-Z0-9_:]*)")
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = {"type": kind, "help": help_text,
                                       "series": {}}
        elif m["type"] != kind:
            raise ValueError(f"metric {name!r} already registered as "
                             f"{m['type']}, not {kind}")
        return m

    def inc(self, name, value=1, help="", **labels):
        """Monotonic counter add (negative increments are a bug)."""
        if value < 0:
            raise ValueError(f"counter {name!r} increment must be >= 0")
        with self._lock:
            s = self._series(name, "counter", help)["series"]
            key = _label_key(labels)
            s[key] = s.get(key, 0) + value

    def set_gauge(self, name, value, help="", **labels):
        with self._lock:
            s = self._series(name, "gauge", help)["series"]
            s[_label_key(labels)] = value

    def observe(self, name, value, buckets=DEFAULT_BUCKETS, help="",
                **labels):
        with self._lock:
            s = self._series(name, "histogram", help)["series"]
            key = _label_key(labels)
            h = s.get(key)
            if h is None:
                h = s[key] = _Hist(buckets)
            h.observe(value)

    def declare_histogram(self, name, buckets=DEFAULT_BUCKETS, help="",
                          **labels):
        """Pre-register a histogram series with zero observations, so a
        dashboard sees the metric (all-zero buckets, ``_count 0``)
        before -- or even without -- the first event. Idempotent;
        an existing series keeps its buckets and counts."""
        with self._lock:
            s = self._series(name, "histogram", help)["series"]
            s.setdefault(_label_key(labels), _Hist(buckets))

    # -- reads -------------------------------------------------------------
    def get(self, name, **labels):
        """Current value of one series (histograms return (sum, count))."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return None
            v = m["series"].get(_label_key(labels))
            if isinstance(v, _Hist):
                return (v.total, v.count)
            return v

    def histogram_buckets(self, name, **labels):
        """Raw bucket layout + per-bucket counts of one histogram
        series: ``((upper_edges..., inf), (counts...,))``, or None for a
        missing series. The pace controller (resilience/steering.py)
        diffs successive snapshots to quantile the *window* between two
        control decisions -- the cumulative distribution would let a
        long quiet phase mask a regime change."""
        with self._lock:
            m = self._metrics.get(name)
            v = (m["series"].get(_label_key(labels))
                 if m is not None else None)
            if not isinstance(v, _Hist):
                return None
            return (v.buckets + (math.inf,), tuple(v.counts))

    def histogram_quantile(self, name, q, **labels):
        """Approximate quantile of one histogram series from its bucket
        counts: the upper edge of the first bucket whose cumulative count
        reaches ``q * count`` (Prometheus' ``histogram_quantile`` without
        interpolation -- conservative, never under-reports a tail).
        Returns None for a missing/empty series; observations past the
        last bucket return ``inf`` (the tail escaped the layout)."""
        if not 0.0 <= float(q) <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            m = self._metrics.get(name)
            v = (m["series"].get(_label_key(labels))
                 if m is not None else None)
            if not isinstance(v, _Hist) or v.count == 0:
                return None
            target = float(q) * v.count
            cum = 0
            for le, c in zip(v.buckets, v.counts):
                cum += c
                if cum >= target:
                    return float(le)
            return math.inf

    def collect(self):
        """Flat ``{"name{label=v}": value}`` of every scalar series
        (histograms expose ``_sum`` and ``_count``)."""
        out = {}
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                for key, v in sorted(m["series"].items()):
                    lbl = _fmt_labels(key)
                    if isinstance(v, _Hist):
                        out[f"{name}_sum{lbl}"] = v.total
                        out[f"{name}_count{lbl}"] = v.count
                    else:
                        out[f"{name}{lbl}"] = v
        return out

    def snapshot_into(self, record, prefix="m/"):
        """Merge every series that changed since the last snapshot into
        ``record`` (in place; existing keys are never overwritten).
        Called by ``MetricsLogger.log`` -- per-round counters surface in
        the round's own metrics record."""
        flat = self.collect()
        for k, v in flat.items():
            if self._last_snapshot.get(k) != v:
                record.setdefault(prefix + k, v)
        self._last_snapshot = flat
        return record

    def render_prometheus(self):
        """Prometheus text exposition format (version 0.0.4)."""
        lines = []
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                if m["help"]:
                    lines.append(f"# HELP {name} {_escape(m['help'])}")
                lines.append(f"# TYPE {name} {m['type']}")
                for key, v in sorted(m["series"].items()):
                    if isinstance(v, _Hist):
                        cum = 0
                        for le, c in zip(v.buckets + (math.inf,), v.counts):
                            cum += c
                            lines.append(
                                f"{name}_bucket"
                                f"{_fmt_labels(key, [('le', _fmt_value(float(le)))])}"
                                f" {cum}")
                        lines.append(
                            f"{name}_sum{_fmt_labels(key)} "
                            f"{_fmt_value(v.total)}")
                        lines.append(
                            f"{name}_count{_fmt_labels(key)} {v.count}")
                    else:
                        lines.append(
                            f"{name}{_fmt_labels(key)} {_fmt_value(v)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def dump_prometheus(self, path):
        with open(path, "w") as f:
            f.write(self.render_prometheus())
        return path


_registry = None


def get_registry():
    """The process-wide registry, or None when observability is off --
    instrumentation points guard with ``if reg is not None``."""
    return _registry


def set_registry(registry):
    global _registry
    prev = _registry
    _registry = registry
    return prev


__all__ = ["MetricsRegistry", "DEFAULT_BUCKETS", "get_registry",
           "set_registry"]
