"""Multi-host execution: jax.distributed + global-array round control.

Reference behavior being replaced (SURVEY.md section 2.8): the reference
scales past one machine with ``mpirun -hostfile mpi_host_file`` launching
one torch process per client and moving pickled state_dicts over MPI
(``fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:
18-38``, ``fedml_core/distributed/communication/mpi/com_manager.py``).
TPU-native design: every host runs the SAME SPMD program over one global
``clients`` mesh; aggregation collectives ride ICI within a slice and DCN
across hosts, with no user-visible message passing. This module is the
(thin) control plane that makes the engine's ``make_sharded_round`` span
processes:

- ``maybe_initialize_distributed()``: env-driven ``jax.distributed``
  bring-up (no-op single-process, so every entry point calls it
  unconditionally).
- ``global_cohort()``: build the globally-sharded cohort arrays from each
  host's full cohort copy (FL cohorts are small host-side; every process
  packs the identical schedule because packing RNG is seeded identically).
- ``gather_metrics()`` / ``is_primary()``: read back client-sharded round
  outputs and gate logging/checkpointing to rank 0 (the reference runs
  wandb on rank 0 only).
"""

from __future__ import annotations

import logging
import os


def maybe_initialize_distributed():
    """Initialize ``jax.distributed`` from environment variables.

    Recognized (first match wins):
      - ``FEDML_TPU_COORDINATOR`` + ``FEDML_TPU_NUM_PROCESSES`` +
        ``FEDML_TPU_PROCESS_ID``: explicit bring-up (the mpirun-hostfile
        analog; works on CPU hosts and TPU pods alike).
      - ``JAX_COORDINATOR_ADDRESS``: defer to jax's own auto-detection
        (TPU pod metadata, SLURM, etc.) via argument-less initialize().

    Returns ``(process_index, process_count)``. Safe to call multiple
    times and in single-process runs (returns ``(0, 1)``).
    """
    import jax

    def init(**kw):
        try:
            jax.distributed.initialize(**kw)
        except RuntimeError as e:
            # tolerate ONLY re-initialization; a connect failure must fail
            # fast -- swallowing it would leave every host running the
            # full workload independently as its own "process 0"
            if "already initialized" not in str(e).lower():
                raise
            logging.debug("jax.distributed already initialized: %s", e)

    coord = os.environ.get("FEDML_TPU_COORDINATOR")
    nproc = os.environ.get("FEDML_TPU_NUM_PROCESSES")
    if coord and nproc and int(nproc) > 1:
        init(coordinator_address=coord, num_processes=int(nproc),
             process_id=int(os.environ["FEDML_TPU_PROCESS_ID"]))
        logging.info("jax.distributed: process %d/%s via %s",
                     jax.process_index(), nproc, coord)
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        init()
    return jax.process_index(), jax.process_count()


def is_primary() -> bool:
    import jax
    return jax.process_index() == 0


def global_cohort(mesh, cohort_data):
    """Place a host-replicated packed cohort onto a (possibly multi-host)
    mesh, sharded over the ``clients`` axis.

    Every process holds the full cohort in host memory and contributes the
    shards its local devices own (``jax.make_array_from_callback``) -- the
    schedule is identical on all processes because the packing RNG stream
    is seeded identically, so no host<->host data exchange is needed
    (contrast: the reference unicasts per-client pickles from rank 0).
    Single-process meshes take the plain ``device_put`` path.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.parallel.mesh import (
        CLIENT_AXIS, pad_cohort_to_multiple, shard_cohort)

    if jax.process_count() == 1:
        return shard_cohort(mesh, cohort_data)
    cohort_data = pad_cohort_to_multiple(cohort_data,
                                         mesh.shape[CLIENT_AXIS])

    def place(x):
        x = np.asarray(x)
        sh = NamedSharding(mesh, P(CLIENT_AXIS))
        return jax.make_array_from_callback(x.shape, sh,
                                            lambda idx: x[idx])

    return jax.tree.map(place, cohort_data)


def global_put(mesh, tree, spec):
    """Place a host-replicated pytree as globally-sharded arrays.

    Every process holds identical host values (same seeds everywhere) and
    contributes the shards its local devices own
    (``jax.make_array_from_callback``); single-process falls back to
    ``device_put``. The generic form of :func:`global_cohort`, used by the
    sp/tp/ep step builders for params (``P()``) and batches."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    def place(x):
        sh = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sh)
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sh,
                                            lambda idx: x[idx])

    return jax.tree.map(place, tree)


def gather_metrics(tree):
    """Fetch round outputs to every host as numpy.

    Replicated leaves read locally; client-sharded leaves are
    all-gathered across processes (``multihost_utils.process_allgather``
    -- the DCN collective replacing MPI gather-to-rank-0)."""
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, tree)

    from jax.experimental import multihost_utils

    def fetch(x):
        if not hasattr(x, "sharding"):
            return np.asarray(x)
        if x.sharding.is_fully_replicated:
            return np.asarray(
                multihost_utils.global_array_to_host_local_array(
                    x, x.sharding.mesh,
                    jax.sharding.PartitionSpec()))
        return np.asarray(multihost_utils.process_allgather(
            x, tiled=True))

    return jax.tree.map(fetch, tree)


def sync(tag: str = "fedml_tpu"):
    """Cross-process barrier (reference: MPI barrier between rounds)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


__all__ = ["maybe_initialize_distributed", "is_primary", "global_cohort",
           "global_put",
           "gather_metrics", "sync"]
