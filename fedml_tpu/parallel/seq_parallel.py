"""Sequence-parallel (sp) training: long-context LM steps over a mesh.

The reference's longest training sequence is an 80-char Shakespeare window
(``fedml_api/model/nlp/rnn.py:4-24``); context length is bounded by one
GPU's memory. Here long context is first-class: the sequence dimension
shards over a ``seq`` mesh axis and attention runs as a ring
(:mod:`fedml_tpu.ops.ring_attention` -- K/V shards rotate over ICI), so
per-chip activation memory is ``O(T / n_seq)`` and context scales with the
mesh, not the chip.

Design (TPU-idiomatic, scaling-book recipe): ONE jitted step; inputs carry
``NamedSharding`` annotations (batch over ``data``, sequence over ``seq``);
XLA/GSPMD lays out every position-wise op (embed, LN, MLP, head, loss)
shard-local and inserts the cross-shard collectives (mean-loss psum, grad
all-reduce) automatically. The only explicit communication is the ring
attention's ``ppermute``, which lives in a ``shard_map`` island inside the
jit. Gradients and optimizer state stay replicated (params are small
relative to long-sequence activations -- the sp axis exists to shard the
``O(B T C)`` terms).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.ops.ring_attention import make_ring_attention

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_seq_mesh(n_data: int, n_seq: int, devices=None):
    """``(data, seq)`` mesh: dp across ``data``, sp across ``seq``."""
    from fedml_tpu.parallel.mesh import make_2d_mesh
    return make_2d_mesh(n_data, n_seq, (DATA_AXIS, SEQ_AXIS), devices)


def seq_parallel_model(model_cls, mesh, *, block_size: int = 512, **kw):
    """Instantiate ``model_cls`` (TransformerLM-compatible) with its
    attention routed through ring attention over ``mesh``'s seq axis."""
    ring = make_ring_attention(mesh, SEQ_AXIS, causal=True,
                               block_size=block_size,
                               batch_axis=DATA_AXIS)
    return model_cls(attention_fn=ring, **kw)


def make_seq_parallel_lm_step(model, mesh, tx: Optional[Any] = None,
                              data_axis: str = DATA_AXIS,
                              seq_axis: str = SEQ_AXIS,
                              aux_loss_weight: float = 0.01):
    """Build ``(init_fn, step_fn)`` for next-token LM training with the
    sequence sharded over ``mesh[seq_axis]``.

    ``step_fn(params, opt_state, idx, tgt) -> (params, opt_state, loss)``
    is jitted with input shardings ``idx/tgt: P(data, seq)`` and replicated
    params; call it with ``[B, T]`` int arrays where ``tgt`` is ``idx``
    shifted globally by one (shift BEFORE sharding -- the shard-boundary
    token's target lives in the next shard, so the shift cannot be done
    shard-locally). ``tgt`` entries < 0 are ignored (loss mask).
    """
    from fedml_tpu.parallel.multihost import global_put

    tx = tx if tx is not None else optax.sgd(1e-3)
    x_sh = NamedSharding(mesh, P(data_axis, seq_axis))
    rep = NamedSharding(mesh, P())

    def init_fn(rng, example_idx):
        # global_put handles multi-host meshes (each process contributes
        # its local shards; params replicate identically from shared seeds)
        vs = model.init(rng, example_idx)
        params = global_put(mesh, vs["params"], P())
        return params, global_put(mesh, tx.init(vs["params"]), P())


    def loss_fn(params, idx, tgt):
        from fedml_tpu.models.transformer import lm_loss
        # collect sown losses (MoE load-balancing aux; 0.0 for dense
        # models) so MoE composes with sequence parallelism
        logits, mut = model.apply({"params": params}, idx,
                                  mutable=["losses"])
        aux = sum(jax.tree.leaves(mut.get("losses", {})), 0.0)
        return lm_loss(logits, tgt) + aux_loss_weight * aux

    @partial(jax.jit,
             in_shardings=(rep, rep, x_sh, x_sh),
             out_shardings=(rep, rep, None),
             donate_argnums=(0, 1))
    def step_fn(params, opt_state, idx, tgt):
        loss, grads = jax.value_and_grad(loss_fn)(params, idx, tgt)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return init_fn, step_fn


def place_lm_batch(mesh, idx, tgt, data_axis: str = DATA_AXIS,
                   seq_axis: str = SEQ_AXIS):
    """Host-replicated ``[B, T]`` batches -> global arrays sharded
    ``P(data, seq)``. Required on multi-host meshes (each process holds
    the identical host batch and contributes its local shards);
    single-process it is a plain sharded device_put."""
    from fedml_tpu.parallel.multihost import global_put

    return (global_put(mesh, idx, P(data_axis, seq_axis)),
            global_put(mesh, tgt, P(data_axis, seq_axis)))


def shift_targets(idx, pad_id: int = -1):
    """Global next-token targets: ``tgt[t] = idx[t+1]``, last position
    masked. Do this on the HOST-side full sequence before sharding."""
    return jnp.concatenate(
        [idx[:, 1:], jnp.full_like(idx[:, :1], pad_id)], axis=1)


__all__ = ["make_seq_mesh", "make_seq_parallel_lm_step", "place_lm_batch",
           "seq_parallel_model", "shift_targets", "DATA_AXIS", "SEQ_AXIS"]
