"""Tensor-parallel (tp) LM training: Megatron-style sharded matmuls.

Complements :mod:`fedml_tpu.parallel.seq_parallel` (sp) and the client-DP
engine (dp): here the model dimension shards over a ``model`` mesh axis --
the TPU-native analog of the reference server's ``nn.DataParallel``
scale-out (``GKTServerTrainer.py:28-29``), but splitting the weights
instead of replicating them.

Design (scaling-book recipe, GSPMD-first): no manual collectives. Params
get ``NamedSharding`` annotations in the Megatron pattern --

- attention qkv / MLP up-projection: output-feature sharded ``P(None,
  model)`` (each shard computes its own heads / hidden slice);
- attention proj / MLP down-projection: input-feature sharded ``P(model,
  None)`` (XLA inserts the one all-reduce per block);
- embeddings, LayerNorms, head: replicated.

The jitted step is the same ``(params, opt, idx, tgt)`` contract as the
sp step; attention runs through the pure-JAX blockwise path (GSPMD
partitions its head dimension; a Pallas kernel would be an opaque
partitioning barrier). dp composes on the ``data`` axis of the same mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.ops.attention import blockwise_attention

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_tp_mesh(n_data: int, n_model: int, devices=None):
    from fedml_tpu.parallel.mesh import make_2d_mesh
    return make_2d_mesh(n_data, n_model, (DATA_AXIS, MODEL_AXIS), devices)


# Megatron placement by EXACT Flax module name (a path COMPONENT, never a
# substring -- a future 'projector' module must not silently become
# row-parallel). Module names from models/transformer.py::_Block.
_COL_PARALLEL = frozenset({"qkv", "mlp_up"})    # output-feature sharded
_ROW_PARALLEL = frozenset({"proj", "mlp_down"})  # input-feature sharded
# >=2D params that are INTENTIONALLY replicated (embeddings, LN-free head,
# MoE experts -- expert sharding belongs to the ep axis, not tp); any other
# >=2D param is unknown to the placement table and raises.
_KNOWN_REPLICATED = frozenset({"tok_embed", "pos_embed", "head", "embedding",
                               "moe"})


def _tp_spec(path: str, ndim: int) -> P:
    parts = path.split("/")
    if ndim < 2:  # biases, LN scales: replicated
        return P()
    if any(p in _COL_PARALLEL for p in parts):
        return P(None, MODEL_AXIS)      # column-parallel
    if any(p in _ROW_PARALLEL for p in parts):
        return P(MODEL_AXIS, None)      # row-parallel
    if any(p in _KNOWN_REPLICATED for p in parts):
        return P()
    raise ValueError(
        f"tp_param_shardings: no Megatron placement known for >=2D param "
        f"'{path}' -- add its module name to _COL_PARALLEL/_ROW_PARALLEL/"
        "_KNOWN_REPLICATED rather than silently replicating")


def tp_param_shardings(params, mesh) -> Any:
    """PyTree of ``NamedSharding`` mirroring ``params``. Validates that
    every sharded dimension divides the ``model`` mesh axis (an indivisible
    dim would make GSPMD pad-and-mask, silently wasting compute)."""
    n_model = mesh.shape[MODEL_AXIS]
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = {}
    for path, leaf in flat:
        key = "/".join(str(p.key) for p in path
                       if hasattr(p, "key"))
        spec = _tp_spec(key, jnp.ndim(leaf))
        for dim, axis in enumerate(spec):
            if axis == MODEL_AXIS and leaf.shape[dim] % n_model:
                raise ValueError(
                    f"tp_param_shardings: '{key}' dim {dim} of size "
                    f"{leaf.shape[dim]} does not divide the {n_model}-way "
                    "model axis")
        specs[key] = NamedSharding(mesh, spec)

    def lookup(path, leaf):
        key = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        return specs[key]

    return jax.tree_util.tree_map_with_path(lookup, params)


def tp_attention(block_size: int = 512):
    """Attention for the tp path: pure-JAX blockwise (flash semantics) so
    GSPMD can split its head dimension across ``model`` shards."""
    def fn(q, k, v):
        return blockwise_attention(q, k, v, causal=True,
                                   block_size=block_size)
    return fn


def make_tp_lm_step(model, mesh, tx: Optional[Any] = None,
                    data_axis: str = DATA_AXIS,
                    aux_loss_weight: float = 0.01):
    """Build ``(init_fn, step_fn)`` with Megatron-sharded params.

    ``init_fn(rng, example_idx) -> (params, opt_state)`` places every
    param/optimizer leaf on its tp sharding; ``step_fn(params, opt_state,
    idx, tgt)`` is jitted with batch sharded over ``data`` and params on
    their tp shardings (outputs keep the same placements, so steps chain
    without resharding).
    """
    tx = tx if tx is not None else optax.sgd(1e-3)
    x_sh = NamedSharding(mesh, P(data_axis, None))
    rep = NamedSharding(mesh, P())

    def init_fn(rng, example_idx):
        vs = model.init(rng, example_idx)
        p_sh = tp_param_shardings(vs["params"], mesh)
        params = jax.tree.map(jax.device_put, vs["params"], p_sh)
        opt_state = tx.init(params)  # optax state mirrors param placements
        return params, opt_state

    def loss_fn(params, idx, tgt):
        from fedml_tpu.models.transformer import lm_loss
        # collect sown losses (MoE load-balancing aux; 0.0 for dense
        # models) so MoE composes with tensor parallelism
        logits, mut = model.apply({"params": params}, idx,
                                  mutable=["losses"])
        aux = sum(jax.tree.leaves(mut.get("losses", {})), 0.0)
        return lm_loss(logits, tgt) + aux_loss_weight * aux

    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, idx, tgt):
        idx = jax.lax.with_sharding_constraint(idx, x_sh)
        tgt = jax.lax.with_sharding_constraint(tgt, x_sh)
        loss, grads = jax.value_and_grad(loss_fn)(params, idx, tgt)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return init_fn, step_fn


__all__ = ["make_tp_mesh", "make_tp_lm_step", "tp_param_shardings",
           "tp_attention", "DATA_AXIS", "MODEL_AXIS"]
