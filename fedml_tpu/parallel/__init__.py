from fedml_tpu.parallel.mesh import make_client_mesh  # noqa: F401
from fedml_tpu.parallel.packing import pack_cohort  # noqa: F401
from fedml_tpu.parallel.engine import (  # noqa: F401
    ClientUpdateConfig,
    make_client_update,
    make_sim_round,
    make_sharded_round,
    make_eval_fn,
)
