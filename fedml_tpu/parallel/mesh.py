"""Device-mesh construction for federated rounds.

The reference maps one FL client to one OS process via ``mpirun -np N+1``
(``run_fedavg_distributed_pytorch.sh:18-38``). Here clients map to shards of a
``clients`` mesh axis; aggregation collectives ride ICI within a slice and DCN
across slices. A second optional ``model`` axis supports tensor-sharding large
server models (FedGKT) without changing the round program.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


CLIENT_AXIS = "clients"
MODEL_AXIS = "model"


def make_2d_mesh(n_a: int, n_b: int, axis_names, devices=None):
    """Generic ``(n_a, n_b)`` device grid -- the shared constructor behind
    the dp x sp / dp x tp / dp x ep meshes (each just names the axes)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    need = n_a * n_b
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    return Mesh(np.array(devices[:need]).reshape(n_a, n_b),
                tuple(axis_names))


def make_client_mesh(n_client_shards=None, n_model_shards=1, devices=None):
    """Build a ``(clients, model)`` mesh over available devices.

    ``n_client_shards`` defaults to all devices / n_model_shards. On a single
    chip this yields a 1x1 mesh -- the same round program runs unchanged, which
    is how standalone simulation and pod execution share one code path.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if n_client_shards is None:
        n_client_shards = len(devices) // n_model_shards
    need = n_client_shards * n_model_shards
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_client_shards, n_model_shards)
    return Mesh(grid, (CLIENT_AXIS, MODEL_AXIS))


def client_sharding(mesh):
    """Sharding for arrays with a leading client axis."""
    return NamedSharding(mesh, P(CLIENT_AXIS))


def replicated_sharding(mesh):
    return NamedSharding(mesh, P())


def zero_pad_leading(tree, pad, xp=np):
    """Zero-pad every leaf's leading (client) axis by ``pad`` rows.

    THE dummy-client invariant, shared by every engine path (WaveRunner
    waves, the flat indexed round's chunk padding, mesh sharding): padded
    clients carry ``n``=0 and fully-masked schedules, so they are
    zero-weight in aggregation and every training step they touch is
    guarded to a no-op. ``xp`` selects numpy (host) or jax.numpy
    (inside jit)."""
    if not pad:
        return tree
    z = lambda a: xp.concatenate(
        [a, xp.zeros((pad,) + a.shape[1:], a.dtype)])
    return jax.tree.map(z, tree)


def pad_cohort_to_multiple(cohort_data, multiple):
    """Pad the cohort's client axis to a multiple of ``multiple`` with
    zero-weight dummy clients, so cohorts that don't divide the mesh still
    shard (``shard_map`` needs even shards)."""
    C = len(next(iter(cohort_data.values())))
    cohort_data = {k: np.asarray(v) for k, v in cohort_data.items()}
    return zero_pad_leading(cohort_data, (-C) % multiple)


def shard_cohort(mesh, cohort_data):
    """Place a packed cohort dict (leading axis = clients) onto the mesh,
    padding to the mesh's client-axis size first when needed."""
    cohort_data = pad_cohort_to_multiple(cohort_data, mesh.shape[CLIENT_AXIS])
    sh = client_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), cohort_data)
