"""Host-side cohort packing: ragged client datasets -> dense SPMD batches.

The hardest part of running federated rounds as one XLA program is client
heterogeneity (SURVEY.md section 7 "Hard parts" #1): LDA shards have wildly
different sizes, but jitted code needs static shapes. We mask-and-pad: every
client's local epoch schedule is materialized as ``[S, B]`` index batches where
``S`` = max steps over the cohort; padded slots carry ``mask=0`` and are
no-ops in the training scan. True sample counts are carried separately so the
weighted aggregation uses the exact ``n_i`` of the reference
(``FedAVGAggregator.py:63-67``).

Shapes are bucketed to the cohort max, so recompilation happens only when the
cohort max-steps bucket changes, not per client.
"""

from __future__ import annotations

import math

import numpy as np


def _per_epoch_steps(n, batch_size, drop_last=False):
    per_epoch = n // batch_size if drop_last else math.ceil(n / batch_size)
    return max(1, per_epoch)


def _steps_for(n, batch_size, epochs, drop_last=False):
    return _per_epoch_steps(n, batch_size, drop_last) * epochs


def pack_cohort(client_datasets, batch_size, epochs, rng=None, drop_last=False,
                step_bucket=8, return_indices=False):
    """Pack a cohort's datasets into dense arrays for one federated round.

    Args:
      client_datasets: list of ``{"x": np.ndarray [n_i, ...], "y": [n_i, ...]}``.
      batch_size: local batch size (reference ``--batch_size``).
      epochs: local epochs E (reference ``--epochs``).
      rng: ``np.random.Generator`` for per-epoch shuffling.
      drop_last: drop ragged final batch (reference DataLoader default keeps it).
      step_bucket: round S up to a multiple of this to stabilize jit shapes.

    Returns:
      dict with ``x [C, S, B, ...]``, ``y [C, S, B, ...]``, ``mask [C, S, B]``
      (float32 0/1), and ``n [C]`` true sample counts. With
      ``return_indices=True``, also ``idx [C, S, B]`` int32 -- each slot's
      index into its client's local dataset (0 where masked), for callers
      that must align per-sample side information across rounds (FedGKT
      teacher logits).
    """
    rng = rng or np.random.default_rng(0)
    C = len(client_datasets)
    if batch_size in (-1, 0):
        # reference full-batch convention (CI equivalence runs wire
        # ``--batch_size -1`` through the run script, CI-script-fedavg.sh:42)
        batch_size = max(1, max(len(d["y"]) for d in client_datasets))
    steps = [_steps_for(len(d["y"]), batch_size, epochs, drop_last)
             for d in client_datasets]
    S = max(steps)
    S = int(math.ceil(S / step_bucket) * step_bucket)

    x0 = np.asarray(client_datasets[0]["x"])
    y0 = np.asarray(client_datasets[0]["y"])
    xs = np.zeros((C, S, batch_size) + x0.shape[1:], x0.dtype)
    ys = np.zeros((C, S, batch_size) + y0.shape[1:], y0.dtype)
    mask = np.zeros((C, S, batch_size), np.float32)
    slot_idx = np.zeros((C, S, batch_size), np.int32)
    n = np.zeros((C,), np.float32)

    for c, d in enumerate(client_datasets):
        x, y = np.asarray(d["x"]), np.asarray(d["y"])
        n_c = len(y)
        n[c] = n_c
        s = 0
        for _ in range(epochs):
            order = rng.permutation(n_c)
            per_epoch = _per_epoch_steps(n_c, batch_size, drop_last)
            for b in range(per_epoch):
                idx = order[b * batch_size:(b + 1) * batch_size]
                k = len(idx)
                if k == 0:  # tiny client: reuse the epoch's data
                    idx = order[:min(n_c, batch_size)]
                    k = len(idx)
                xs[c, s, :k] = x[idx]
                ys[c, s, :k] = y[idx]
                mask[c, s, :k] = 1.0
                slot_idx[c, s, :k] = idx
                s += 1
        # remaining [s, S) steps stay fully masked
    out = {"x": xs, "y": ys, "mask": mask, "n": n}
    if return_indices:
        out["idx"] = slot_idx
    return out


def pack_eval(data, batch_size, pad_multiple=1):
    """Pack a flat eval set into ``[S, B]`` masked batches."""
    x, y = np.asarray(data["x"]), np.asarray(data["y"])
    n = len(y)
    if batch_size in (-1, 0):
        batch_size = max(1, n)
    S = max(1, math.ceil(n / batch_size))
    S = int(math.ceil(S / pad_multiple) * pad_multiple)
    xs = np.zeros((S, batch_size) + x.shape[1:], x.dtype)
    ys = np.zeros((S, batch_size) + y.shape[1:], y.dtype)
    mask = np.zeros((S, batch_size), np.float32)
    for s in range(min(S, math.ceil(n / batch_size))):
        idx = np.arange(s * batch_size, min((s + 1) * batch_size, n))
        xs[s, :len(idx)] = x[idx]
        ys[s, :len(idx)] = y[idx]
        mask[s, :len(idx)] = 1.0
    return {"x": xs, "y": ys, "mask": mask}
