"""Host-side cohort packing: ragged client datasets -> dense SPMD batches.

The hardest part of running federated rounds as one XLA program is client
heterogeneity (SURVEY.md section 7 "Hard parts" #1): LDA shards have wildly
different sizes, but jitted code needs static shapes. We mask-and-pad: every
client's local epoch schedule is materialized as ``[S, B]`` index batches where
``S`` = max steps over the cohort; padded slots carry ``mask=0`` and are
no-ops in the training scan. True sample counts are carried separately so the
weighted aggregation uses the exact ``n_i`` of the reference
(``FedAVGAggregator.py:63-67``).

Shapes are bucketed to the cohort max, so recompilation happens only when the
cohort max-steps bucket changes, not per client.
"""

from __future__ import annotations

import math
import os

import numpy as np


def packing_backend(native="auto") -> str:
    """Resolve which schedule generator runs: ``"native"`` (C++ shim) or
    ``"python"`` (numpy).

    The choice is EXPLICIT and machine-stable: ``auto`` means "native iff
    the shim built/loaded", overridable by the ``FEDML_TPU_PACKING`` env var
    or a ``native=True/False`` argument -- never by ``os.cpu_count()`` (a
    round-1 advisor finding: a load-dependent gate made shuffle
    realizations machine-dependent in a way nothing recorded). The resolved
    name is checkpointed alongside the data-RNG state so resume detects a
    backend switch instead of silently changing schedules (the two
    backends use different PRNG families).
    """
    if native is True:
        return "native"
    if native is False:
        return "python"
    env = os.environ.get("FEDML_TPU_PACKING", "auto").lower()
    if env in ("native", "python"):
        return env
    from fedml_tpu.native import native_available
    return "native" if native_available() else "python"


def _per_epoch_steps(n, batch_size, drop_last=False):
    per_epoch = n // batch_size if drop_last else math.ceil(n / batch_size)
    return max(1, per_epoch)


def _steps_for(n, batch_size, epochs, drop_last=False):
    return _per_epoch_steps(n, batch_size, drop_last) * epochs


def pack_cohort(client_datasets, batch_size, epochs, rng=None, drop_last=False,
                step_bucket=8, return_indices=False, native="auto"):
    """Pack a cohort's datasets into dense arrays for one federated round.

    Args:
      client_datasets: list of ``{"x": np.ndarray [n_i, ...], "y": [n_i, ...]}``.
      batch_size: local batch size (reference ``--batch_size``).
      epochs: local epochs E (reference ``--epochs``).
      rng: ``np.random.Generator`` for per-epoch shuffling.
      drop_last: drop ragged final batch (reference DataLoader default keeps it).
      step_bucket: round S up to a multiple of this to stabilize jit shapes.

    Returns:
      dict with ``x [C, S, B, ...]``, ``y [C, S, B, ...]``, ``mask [C, S, B]``
      (float32 0/1), and ``n [C]`` true sample counts. With
      ``return_indices=True``, also ``idx [C, S, B]`` int32 -- each slot's
      index into its client's local dataset (0 where masked), for callers
      that must align per-sample side information across rounds (FedGKT
      teacher logits).
    """
    rng = rng or np.random.default_rng(0)
    C = len(client_datasets)
    if batch_size in (-1, 0):
        # reference full-batch convention (CI equivalence runs wire
        # ``--batch_size -1`` through the run script, CI-script-fedavg.sh:42)
        batch_size = max(1, max(len(d["y"]) for d in client_datasets))
    steps = [_steps_for(len(d["y"]), batch_size, epochs, drop_last)
             for d in client_datasets]
    S = max(steps)
    S = int(math.ceil(S / step_bucket) * step_bucket)

    # Exactly ONE draw from the caller's generator regardless of which
    # implementation runs below: the checkpointable host stream advances
    # identically everywhere, so resume keeps a consistent RNG trajectory.
    # (Shuffle *realizations* differ between the native and python PRNG
    # families; ``packing_backend`` makes the choice explicit and
    # checkpoint-verified rather than machine-load-dependent.)
    seed = int(rng.integers(0, 2 ** 63 - 1))
    if packing_backend(native) == "native" and not drop_last:
        from fedml_tpu.native import native_pack_cohort
        out = native_pack_cohort(client_datasets, batch_size, epochs, S, seed)
        if out is not None:
            if not return_indices:
                out.pop("idx")
            return out
    rng = np.random.default_rng(seed)

    x0 = np.asarray(client_datasets[0]["x"])
    y0 = np.asarray(client_datasets[0]["y"])
    xs = np.zeros((C, S, batch_size) + x0.shape[1:], x0.dtype)
    ys = np.zeros((C, S, batch_size) + y0.shape[1:], y0.dtype)
    mask = np.zeros((C, S, batch_size), np.float32)
    slot_idx = np.zeros((C, S, batch_size), np.int32)
    n = np.zeros((C,), np.float32)

    for c, d in enumerate(client_datasets):
        x, y = np.asarray(d["x"]), np.asarray(d["y"])
        n_c = len(y)
        n[c] = n_c
        s = 0
        for _ in range(epochs):
            order = rng.permutation(n_c)
            per_epoch = _per_epoch_steps(n_c, batch_size, drop_last)
            for b in range(per_epoch):
                idx = order[b * batch_size:(b + 1) * batch_size]
                k = len(idx)
                if k == 0:  # tiny client: reuse the epoch's data
                    idx = order[:min(n_c, batch_size)]
                    k = len(idx)
                xs[c, s, :k] = x[idx]
                ys[c, s, :k] = y[idx]
                mask[c, s, :k] = 1.0
                slot_idx[c, s, :k] = idx
                s += 1
        # remaining [s, S) steps stay fully masked
    out = {"x": xs, "y": ys, "mask": mask, "n": n}
    if return_indices:
        out["idx"] = slot_idx
    return out


def stack_clients(client_datasets, n_max=None):
    """Pad-and-stack client shards into device-uploadable arrays.

    Returns ``{"x": [C, n_max, ...], "y": [C, n_max, ...], "n": [C]}`` --
    uploaded to HBM ONCE; afterwards every round needs only a (tiny) index
    schedule from ``pack_schedule``. Padding rows are zeros; they are never
    addressed by a valid schedule slot.
    """
    C = len(client_datasets)
    if n_max is None:
        n_max = max(1, max(len(d["y"]) for d in client_datasets))
    x0 = np.asarray(client_datasets[0]["x"])
    y0 = np.asarray(client_datasets[0]["y"])
    xs = np.zeros((C, n_max) + x0.shape[1:], x0.dtype)
    ys = np.zeros((C, n_max) + y0.shape[1:], y0.dtype)
    n = np.zeros((C,), np.float32)
    for c, d in enumerate(client_datasets):
        k = len(d["y"])
        n[c] = k
        xs[c, :k] = np.asarray(d["x"])
        ys[c, :k] = np.asarray(d["y"])
    return {"x": xs, "y": ys, "n": n}


def pack_schedule(ns, batch_size, epochs, rng=None, drop_last=False,
                  step_bucket=8, native="auto", s_max=None):
    """Index schedule only -- no data movement.

    Args: ``ns`` per-client sample counts. Returns ``{"idx": [C, S, B]
    int32, "mask": [C, S, B] float32, "n": [C] float32}`` with the same
    epoch/batch semantics as ``pack_cohort``. The C++ shim generates it
    when available; the numpy fallback shares semantics (shuffles differ --
    different RNG families -- but both are seeded from the same host
    generator so runs stay reproducible/resumable). ``s_max`` forces the
    step axis to a caller-chosen length (the bucketed streaming path pins
    it to the bucket edge so every chunk of a bucket shares ONE compiled
    shape); it must cover the cohort's true maximum.
    """
    rng = rng or np.random.default_rng(0)
    ns = [int(v) for v in ns]
    C = len(ns)
    if batch_size in (-1, 0):
        batch_size = max(1, max(ns))
    true_max = max(_steps_for(n, batch_size, epochs, drop_last) for n in ns)
    S = int(math.ceil(true_max / step_bucket) * step_bucket)
    if s_max is not None:
        if int(s_max) < true_max:
            raise ValueError(f"s_max={s_max} below the cohort's true max "
                             f"step count {true_max}")
        S = int(s_max)
    B = batch_size

    # one-draw contract and backend resolution identical to pack_cohort's,
    # so the two functions consume the host RNG the same way and produce
    # the same schedules on a given machine -- keeping schedule-equality
    # invariants (hierarchical 1-group == fedavg) across data paths
    seed = int(rng.integers(0, 2 ** 63 - 1))
    if packing_backend(native) == "native" and not drop_last:
        from fedml_tpu.native import native_pack_schedule
        out = native_pack_schedule(ns, B, epochs, S, seed)
        if out is not None:
            return out
    rng = np.random.default_rng(seed)

    idx = np.zeros((C, S, B), np.int32)
    mask = np.zeros((C, S, B), np.float32)
    for c, n_c in enumerate(ns):
        if n_c == 0:
            continue
        s = 0
        for _ in range(epochs):
            order = rng.permutation(n_c)
            for b in range(_per_epoch_steps(n_c, B, drop_last)):
                sel = order[b * B:(b + 1) * B]
                if len(sel) == 0:
                    sel = order[:min(n_c, B)]
                idx[c, s, :len(sel)] = sel
                mask[c, s, :len(sel)] = 1.0
                s += 1
    return {"idx": idx, "mask": mask,
            "n": np.asarray(ns, np.float32)}


def lane_max_load(steps_per_client, n_lanes) -> int:
    """Max lane load under the same LPT assignment ``pack_lanes`` uses --
    the cheap first-pass sizing query (no schedule arrays are built)."""
    steps = np.asarray(steps_per_client, np.int64)
    order = np.argsort(-steps, kind="stable")
    K = max(1, min(int(n_lanes), len(steps)))
    loads = np.zeros(K, np.int64)
    for c in order:
        loads[int(np.argmin(loads))] += int(steps[c])
    return int(loads.max())


def pack_lanes(sched, n_lanes, step_bucket=8, l_max=None, native="auto"):
    """Re-lay a packed cohort schedule ``[C, S, B]`` into ``n_lanes``
    PACKED LANES for single-dispatch rounds (``engine.LaneRunner``).

    Clients are assigned to lanes by LPT (longest-processing-time-first)
    scheduling, then each lane's clients run back-to-back: the engine
    resets carried state to the global model at client boundaries and
    flushes the finished client's weighted payload into an accumulator.
    Executed wall steps drop from ``sum_w max_steps(wave_w)`` (waves) to
    ``max_lane_load ~= ceil(total_steps / n_lanes) + LPT slack`` -- the
    endgame of the straggler problem the reference pays with idle GPU
    workers (its slowest client process gates every round).

    Args:
      sched: ``pack_schedule`` output (``idx``/``mask`` ``[C, S, B]``,
        ``n [C]``) in cohort order.
    Returns dict of numpy arrays, lane-major:
      ``idx/mask [K, L, B]``: per-step batch index/mask rows.
      ``slot [K, L]`` int32: cohort position of the step's client (0 on
        padding; masked steps are guarded no-ops).
      ``local_step [K, L]`` int32: step index within the client (drives
        the per-client RNG stream exactly as the flat paths).
      ``flush [K, L]`` float32: 1.0 on a client's final step.
      ``flush_n / flush_steps [K, L]`` float32: the client's sample count
        and executed-step count, carried on its flush step (payload aux).
      ``trip`` int: executed steps per lane (max lane load, bucketed).
    """
    idx, mask = np.asarray(sched["idx"]), np.asarray(sched["mask"])
    ns = np.asarray(sched["n"], np.float32)
    C, S, B = idx.shape
    steps_pc = (mask.sum(axis=2) > 0).sum(axis=1).astype(np.int64)

    # LPT: biggest client first onto the lightest lane
    order = np.argsort(-steps_pc, kind="stable")
    K = max(1, min(int(n_lanes), C))
    loads = np.zeros(K, np.int64)
    lanes = [[] for _ in range(K)]
    for c in order:
        k = int(np.argmin(loads))
        lanes[k].append(int(c))
        loads[k] += int(steps_pc[c])
    L = int(loads.max())
    L = int(math.ceil(max(L, 1) / step_bucket) * step_bucket)
    if l_max is not None:
        # caller-forced allocation length (sharded lanes pad every shard
        # to one uniform L so the SPMD arrays stack)
        if l_max < loads.max():
            raise ValueError(f"l_max={l_max} < max lane load {loads.max()}")
        L = int(l_max)

    if packing_backend(native) == "native":
        # the heavy part -- the O(C*S*B) lane-major relayout -- runs in
        # the C++ shim (threaded per lane); the LPT above is O(C log C)
        # host numpy either way. Output is byte-equal to the loop below.
        from fedml_tpu.native import native_pack_lanes_fill
        members = np.asarray([c for ms in lanes for c in ms], np.int64)
        offsets = np.zeros(K + 1, np.int64)
        np.cumsum([len(ms) for ms in lanes], out=offsets[1:])
        out = native_pack_lanes_fill(idx, mask, ns, steps_pc, members,
                                     offsets, K, L)
        if out is not None:
            out["trip"] = int(loads.max())
            return out

    out_idx = np.zeros((K, L, B), np.int32)
    out_mask = np.zeros((K, L, B), np.float32)
    slot = np.zeros((K, L), np.int32)
    local_step = np.zeros((K, L), np.int32)
    flush = np.zeros((K, L), np.float32)
    flush_n = np.zeros((K, L), np.float32)
    flush_steps = np.zeros((K, L), np.float32)
    for k, members in enumerate(lanes):
        pos = 0
        for c in members:
            s_c = int(steps_pc[c])
            if s_c == 0:
                continue
            sl = slice(pos, pos + s_c)
            out_idx[k, sl] = idx[c, :s_c]
            out_mask[k, sl] = mask[c, :s_c]
            slot[k, sl] = c
            local_step[k, sl] = np.arange(s_c)
            flush[k, pos + s_c - 1] = 1.0
            flush_n[k, pos + s_c - 1] = ns[c]
            flush_steps[k, pos + s_c - 1] = s_c
            pos += s_c
    return {"idx": out_idx, "mask": out_mask, "slot": slot,
            "local_step": local_step, "flush": flush, "flush_n": flush_n,
            "flush_steps": flush_steps, "trip": int(loads.max())}


def parse_bucket_edges(spec, s_max, step_bucket=8):
    """Resolve a ``--bucket_edges`` spec into sorted step-count edges.

    ``spec`` is ``None``/``"geometric"``/``"geo"`` for power-of-two edges
    ``[b, 2b, 4b, ...]`` (b = ``step_bucket``) covering ``s_max``, or an
    explicit comma list (``"8,16,48"``). Explicit lists that stop short of
    ``s_max`` are extended geometrically (doubling the last edge) so every
    client has a bucket -- a client can exceed the top edge mid-run only
    if the caller sized edges from a stale population, and silently
    truncating its schedule would be a correctness bug.

    Edges are jit-shape anchors: one compiled program per edge, so the
    list should be short (geometric gives ``O(log s_max)``) and STABLE
    across rounds -- size it from the population's max step count, not a
    cohort's.
    """
    s_max = max(1, int(s_max))
    if spec is None or str(spec).strip().lower() in ("geometric", "geo",
                                                     "auto", ""):
        edges = [int(step_bucket)]
        while edges[-1] < s_max:
            edges.append(edges[-1] * 2)
        return edges
    edges = sorted({int(v) for v in str(spec).split(",") if str(v).strip()})
    if not edges or any(e <= 0 for e in edges):
        raise ValueError(f"invalid bucket edge spec {spec!r}")
    while edges[-1] < s_max:
        edges.append(edges[-1] * 2)
    return edges


def bucket_edge_for(steps, edges):
    """THE edge-assignment rule of the bucketed streaming engine: the
    smallest edge covering ``steps`` (vector or scalar) -- a step count
    exactly ON an edge lands in that edge's bucket, no off-by-one
    padding to the next one. Raises when any step count exceeds the top
    edge (silently truncating a client's schedule would be a correctness
    bug; size edges from the population max)."""
    steps = np.asarray(steps, np.int64)
    edge_arr = np.asarray(sorted(int(e) for e in edges), np.int64)
    if steps.size and int(steps.max()) > edge_arr[-1]:
        raise ValueError(
            f"client with {int(steps.max())} steps exceeds the top bucket "
            f"edge {edge_arr[-1]} (size edges from the population max)")
    return edge_arr[np.searchsorted(edge_arr, steps, side="left")]


def gather_batches(datasets, sched, members):
    """Materialize a schedule's batches from raw client shards:
    ``xb[c, s, b] = datasets[members[c]]["x"][sched["idx"][c, s, b]]``.

    This is the streaming path's host->device staging unit -- called per
    chunk, so peak host memory is one chunk's batches, never the cohort's
    (the cohort axis is unbounded). Masked slots gather row 0 of their
    client; the mask zeroes their loss contribution downstream.
    """
    idx = np.asarray(sched["idx"])
    C, S, B = idx.shape
    x0 = np.asarray(datasets[members[0]]["x"])
    y0 = np.asarray(datasets[members[0]]["y"])
    xb = np.zeros((C, S, B) + x0.shape[1:], x0.dtype)
    yb = np.zeros((C, S, B) + y0.shape[1:], y0.dtype)
    for c, m in enumerate(members):
        d = datasets[m]
        x, y = np.asarray(d["x"]), np.asarray(d["y"])
        if len(y) == 0:
            continue
        xb[c] = x[idx[c]]
        yb[c] = y[idx[c]]
    return xb, yb


def pack_eval(data, batch_size, pad_multiple=1):
    """Pack a flat eval set into ``[S, B]`` masked batches."""
    x, y = np.asarray(data["x"]), np.asarray(data["y"])
    n = len(y)
    if batch_size in (-1, 0):
        batch_size = max(1, n)
    S = max(1, math.ceil(n / batch_size))
    S = int(math.ceil(S / pad_multiple) * pad_multiple)
    xs = np.zeros((S, batch_size) + x.shape[1:], x.dtype)
    ys = np.zeros((S, batch_size) + y.shape[1:], y.dtype)
    mask = np.zeros((S, batch_size), np.float32)
    for s in range(min(S, math.ceil(n / batch_size))):
        idx = np.arange(s * batch_size, min((s + 1) * batch_size, n))
        xs[s, :len(idx)] = x[idx]
        ys[s, :len(idx)] = y[idx]
        mask[s, :len(idx)] = 1.0
    return {"x": xs, "y": ys, "mask": mask}
