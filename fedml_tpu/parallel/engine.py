"""The federated round engine: one XLA program per round.

Reference behavior being replaced (SURVEY.md section 3.1): the server unicasts
pickled state_dicts to N client processes, each runs E epochs of local SGD,
sends weights back, and the server loops over state_dict keys on CPU.  Here
the entire round --

    per-client local-epochs ``lax.scan``  ->  weighted aggregation  ->  server step

-- is a single jitted function. Client parallelism is ``vmap`` on one chip
(standalone simulation, reference ``fedml_api/standalone/fedavg``) or
``shard_map`` over a ``clients`` mesh axis (distributed, reference
``fedml_api/distributed/fedavg``) with the weighted average as ``psum`` over
ICI. Both placements share the same ``client_update`` and the same
aggregator hooks, so every FL algorithm written against this engine runs in
both paradigms -- the reference needed two separate implementations per
algorithm (sections 2.2 vs 2.3).

Aggregator hooks (see ``fedml_tpu.algorithms``):
  payload_fn(local_state, global_state, aux) -> payload pytree
      per-client transform before averaging (identity for FedAvg, norm-clip
      for robust FedAvg, normalized delta for FedNova).
  server_fn(global_state, avg_payload, server_state, rng) -> (new_global, new_server_state)
      global update from the weighted-average payload (identity for FedAvg,
      optimizer step on the pseudo-gradient for FedOpt).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from fedml_tpu.core import pytree
from fedml_tpu.core.sharding import shard_map
from fedml_tpu.core.trainer import TrainSpec
from fedml_tpu.observability.tracing import get_tracer
from fedml_tpu.parallel.mesh import CLIENT_AXIS, zero_pad_leading


@dataclasses.dataclass(frozen=True)
class ClientUpdateConfig:
    """Local-training hyperparameters (reference flags
    ``--client_optimizer --lr --wd``, ``main_fedavg.py:46-105``; optimizer
    construction parity with ``MyModelTrainer.py:25-31`` -- plain SGD or
    Adam(amsgrad) with weight decay, fresh optimizer state every round)."""
    optimizer: str = "sgd"
    lr: float = 0.03
    weight_decay: float = 0.0
    momentum: float = 0.0
    grad_clip: Optional[float] = None  # FedNAS clips local grads at 5.0


def make_optimizer(cfg: ClientUpdateConfig) -> optax.GradientTransformation:
    txs = []
    if cfg.grad_clip:
        txs.append(optax.clip_by_global_norm(cfg.grad_clip))
    if cfg.optimizer == "sgd":
        # torch.optim.SGD couples weight decay into the gradient
        if cfg.weight_decay:
            txs.append(optax.add_decayed_weights(cfg.weight_decay))
        txs.append(optax.sgd(cfg.lr, momentum=cfg.momentum or None))
    elif cfg.optimizer == "adam":
        # reference uses Adam(amsgrad=True, wd) -- MyModelTrainer.py:29-31;
        # torch couples wd into the gradient BEFORE the Adam statistics
        if cfg.weight_decay:
            txs.append(optax.add_decayed_weights(cfg.weight_decay))
        txs.append(optax.amsgrad(cfg.lr))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer}")
    return optax.chain(*txs)


def _split_state(state):
    params = state["params"]
    rest = {k: v for k, v in state.items() if k != "params"}
    return params, rest


def _tree_select(pred, new, old):
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), new, old)


def make_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Build the jittable per-client local-training function.

    Returns ``fn(global_state, client_data, rng) -> (local_state, aux)`` where
    ``client_data`` is one client's slice of a packed cohort
    (``x [S,B,...], y [S,B,...], mask [S,B], n []``) and ``aux`` carries the
    true sample count ``n`` and executed step count ``steps`` (FedNova's tau).
    Fully-masked (padded) steps leave all carried state untouched.
    """
    optimizer = make_optimizer(cfg)

    def client_update(global_state, client_data, rng):
        params, rest = _split_state(global_state)
        opt_state = optimizer.init(params)
        S = client_data["mask"].shape[0]

        def step(carry, xs):
            params, rest, opt_state = carry
            batch, step_idx = xs
            step_rng = jax.random.fold_in(rng, step_idx)
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            (loss, (new_state, metrics)), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(params)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"]) > 0
            new_carry = _tree_select(valid, (new_params, new_rest, new_opt),
                                     (params, rest, opt_state))
            return new_carry, metrics

        batches = {k: client_data[k] for k in ("x", "y", "mask")}
        (params, rest, _), metrics = jax.lax.scan(
            step, (params, rest, opt_state), (batches, jnp.arange(S)))
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(client_data["mask"] > 0, axis=-1))
        aux = {"n": client_data["n"], "steps": steps_done}
        # metrics leaves are [S, ...] per-step sums; padded steps contributed 0
        metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
        return local_state, aux, metrics_sum

    return client_update


def make_indexed_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Per-client local training over DEVICE-RESIDENT data.

    ``fn(global_state, data, sched, rng)`` where ``data`` is the client's
    full padded shard ``{"x": [n_max, ...], "y": [n_max, ...]}`` living in
    HBM and ``sched`` is a host-built index schedule ``{"idx": [S, B] int32,
    "mask": [S, B], "n": []}``. Each scan step *gathers* its batch on device
    (``jnp.take``), so the host stages bytes once per run instead of
    ``epochs x dataset`` copies per round -- the fix for SURVEY.md section 7
    hard part #2 (client-state swap without stalling).
    """
    optimizer = make_optimizer(cfg)

    def client_update(global_state, data, sched, rng):
        params, rest = _split_state(global_state)
        opt_state = optimizer.init(params)
        S = sched["mask"].shape[0]

        def step(carry, xs):
            params, rest, opt_state = carry
            idx_b, mask_b, step_idx = xs
            batch = {"x": jnp.take(data["x"], idx_b, axis=0),
                     "y": jnp.take(data["y"], idx_b, axis=0),
                     "mask": mask_b}
            step_rng = jax.random.fold_in(rng, step_idx)
            if spec.augment_fn is not None:
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            (loss, (new_state, metrics)), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(params)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(mask_b) > 0
            new_carry = _tree_select(valid, (new_params, new_rest, new_opt),
                                     (params, rest, opt_state))
            return new_carry, metrics

        (params, rest, _), metrics = jax.lax.scan(
            step, (params, rest, opt_state),
            (sched["idx"], sched["mask"], jnp.arange(S)))
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(sched["mask"] > 0, axis=-1))
        aux = {"n": sched["n"], "steps": steps_done}
        metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
        return local_state, aux, metrics_sum

    return client_update


def make_loop_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Per-client local training as a ``fori_loop`` with a DYNAMIC trip count.

    ``fn(global_state, data, sched, steps, rng) -> (local_state, aux,
    metrics_sum)``. Unlike :func:`make_indexed_client_update`'s fixed-length
    ``scan``, the step loop runs exactly ``steps`` iterations where ``steps``
    is a *traced scalar* -- so one compiled program serves every wave length,
    and steps past a wave's true maximum are never executed at all (instead
    of executing fully-masked fwd+bwd no-ops). Metrics accumulate as running
    sums in the carry; schedule rows are fetched with ``dynamic_index_in_dim``.
    """
    optimizer = make_optimizer(cfg)

    def client_update(global_state, data, sched, steps, rng):
        params, rest = _split_state(global_state)
        opt_state = optimizer.init(params)

        def batch_at(i):
            idx_b = jax.lax.dynamic_index_in_dim(
                sched["idx"], i, axis=0, keepdims=False)
            mask_b = jax.lax.dynamic_index_in_dim(
                sched["mask"], i, axis=0, keepdims=False)
            return {"x": jnp.take(data["x"], idx_b, axis=0),
                    "y": jnp.take(data["y"], idx_b, axis=0),
                    "mask": mask_b}

        def grad_at(params, rest, batch, step_rng):
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            return jax.value_and_grad(loss_wrapper, has_aux=True)(params)

        # metric-structure discovery: abstract-eval one step, carry zeros
        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(
                lambda: grad_at(params, rest, batch_at(0), rng))[0][1][1])

        def body(i, carry):
            params, rest, opt_state, msum = carry
            batch = batch_at(i)
            step_rng = jax.random.fold_in(rng, i)
            (_, (new_state, metrics)), grads = grad_at(
                params, rest, batch, step_rng)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"]) > 0
            params, rest, opt_state = _tree_select(
                valid, (new_params, new_rest, new_opt),
                (params, rest, opt_state))
            msum = jax.tree.map(jnp.add, msum, metrics)
            return (params, rest, opt_state, msum)

        params, rest, _, msum = jax.lax.fori_loop(
            0, steps, body, (params, rest, opt_state, metrics0))
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(sched["mask"] > 0, axis=-1))
        aux = {"n": sched["n"], "steps": steps_done}
        return local_state, aux, msum

    return client_update


class WaveRunner:
    """Size-sorted wave execution of a federated round over device-resident
    data -- the throughput path for single-chip cohorts.

    The flat ``make_indexed_sim_round`` pads every client to the cohort-max
    step count, so under a skewed LDA partition most clients burn most steps
    on fully-masked fwd+bwd no-ops. Here the cohort is sorted by true step
    count and dispatched in waves of ``client_chunk`` clients; each wave runs
    one jitted program whose ``fori_loop`` trip count is the *wave* maximum
    (a traced scalar -- no recompilation across waves or rounds). Weighted
    payload sums accumulate on device across waves; a final jitted step
    normalizes and applies ``server_fn``. Total executed steps drop from
    ``C x S_max`` to ``sum_w k x S_w`` -- the padding-waste fix for the
    reference's straggler problem (its MPI path simply blocks on the slowest
    client process, ``FedAVGAggregator.py:58-87``).

    Consumes the SAME ``pack_schedule`` output (same host-RNG draw) as the
    flat path, so switching paths never perturbs the data stream, and
    checkpoints resume across either.
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig,
                 payload_fn=None, server_fn=None, client_chunk=8):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.client_chunk = int(client_chunk or 8)
        client_update = make_loop_client_update(spec, cfg)
        payload_fn_ = self.payload_fn
        server_fn_ = self.server_fn

        @jax.jit
        def wave_fn(global_state, device_x, device_y, ids, sched, steps, rngs):
            data = {"x": jnp.take(device_x, ids, axis=0),
                    "y": jnp.take(device_y, ids, axis=0)}
            local_states, aux, metrics = jax.vmap(
                client_update, in_axes=(None, 0, 0, None, 0))(
                    global_state, data, sched, steps, rngs)
            payloads = jax.vmap(payload_fn_, in_axes=(0, None, 0))(
                local_states, global_state, aux)
            w = aux["n"].astype(jnp.float32)
            pay_sum = jax.tree.map(
                lambda x: jnp.tensordot(w, x.astype(jnp.float32), axes=(0, 0)),
                payloads)
            metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
            return pay_sum, jnp.sum(w), metrics_sum, aux

        @jax.jit
        def add_fn(a, b):
            return jax.tree.map(jnp.add, a, b)

        @jax.jit
        def finish_fn(global_state, server_state, pay_sum, w_sum, dtypes, rng):
            # weighted mean over the accumulated sums. NOTE: unlike
            # pytree.tree_weighted_mean there is no uniform fallback here --
            # an all-empty cohort (w_sum == 0) yields a zero payload, so
            # callers MUST fail fast on empty cohorts before dispatch
            # (FedAvgAPI.train_one_round raises; direct users take note)
            avg = jax.tree.map(
                lambda s, d: (s / jnp.maximum(w_sum, 1e-12)).astype(d.dtype),
                pay_sum, dtypes)
            return server_fn_(global_state, avg, server_state, rng)

        self._wave_fn = wave_fn
        self._add_fn = add_fn
        self._finish_fn = finish_fn
        self._dtypes = None

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def run_round(self, global_state, server_state, device_data, ids, sched,
                  rng):
        """One federated round.

        Args:
          device_data: ``{"x": [N_rows, ...], "y": [N_rows, ...]}`` full
            client shards resident in HBM (``stack_clients`` output).
          ids: cohort client rows into ``device_data`` (cohort order).
          sched: full packed schedule (``pack_schedule`` output, numpy,
            cohort order) -- ``{"idx" [C,S,B], "mask" [C,S,B], "n" [C]}``.
          rng: round PRNG key; per-client keys derive exactly as in the flat
            paths (``split(fold_in(rng, 1), C)`` indexed by cohort slot), so
            wave and flat trajectories agree to float reassociation.
        """
        import numpy as np

        mask = np.asarray(sched["mask"])
        C = mask.shape[0]
        steps_per_client = (mask.sum(axis=2) > 0).sum(axis=1).astype(np.int64)
        order = np.argsort(-steps_per_client, kind="stable")
        chunk = min(self.client_chunk, C)
        all_rngs = np.asarray(jax.random.split(jax.random.fold_in(rng, 1), C))
        ids = np.asarray(ids, np.int32)
        sched_idx = np.asarray(sched["idx"])
        sched_n = np.asarray(sched["n"], np.float32)

        acc = None
        wave_aux, wave_pos = [], []
        for w0 in range(0, C, chunk):
            pos = order[w0:w0 + chunk]
            k = len(pos)
            trip = int(steps_per_client[pos].max())
            w_idx, w_mask = sched_idx[pos], mask[pos]
            w_n, w_ids, w_rngs = sched_n[pos], ids[pos], all_rngs[pos]
            if k < chunk:  # pad the ragged last wave -> one stable jit shape
                pad = chunk - k
                w_idx, w_mask, w_n, w_ids = zero_pad_leading(
                    (w_idx, w_mask, w_n, w_ids), pad)
                w_rngs = np.concatenate([w_rngs, w_rngs[:1].repeat(pad, 0)])
            ws = {"idx": jnp.asarray(w_idx), "mask": jnp.asarray(w_mask),
                  "n": jnp.asarray(w_n)}
            # span measures dispatch (async): device time for the whole
            # round lands in the caller's end-of-round sync
            with get_tracer().span("wave", clients=int(k), trip=trip):
                pay_sum, w_sum, metrics_sum, aux = self._wave_fn(
                    global_state, device_data["x"], device_data["y"],
                    jnp.asarray(w_ids), ws, jnp.int32(trip),
                    jnp.asarray(w_rngs))
            part = (pay_sum, w_sum, metrics_sum)
            acc = part if acc is None else self._add_fn(acc, part)
            wave_aux.append(aux)
            wave_pos.append(pos)

        pay_sum, w_sum, metrics_sum = acc
        with get_tracer().span("server-update"):
            new_global, new_server_state = self._finish_fn(
                global_state, server_state, pay_sum, w_sum,
                self._payload_dtypes(global_state),
                jax.random.fold_in(rng, 2))

        # gather per-client aux back into cohort order (host, post-dispatch)
        aux_out = {"n": np.zeros(C, np.float32),
                   "steps": np.zeros(C, np.int64)}
        for pos, aux in zip(wave_pos, wave_aux):
            k = len(pos)
            aux_out["n"][pos] = np.asarray(aux["n"])[:k]
            aux_out["steps"][pos] = np.asarray(aux["steps"])[:k]
        return new_global, new_server_state, {"aux": aux_out,
                                              "metrics": metrics_sum}


def make_lane_update(spec: TrainSpec, cfg: ClientUpdateConfig, payload_fn):
    """Build the per-lane sequential-clients update (shared by
    :class:`LaneRunner` and :class:`ShardedLaneRunner`).

    ``fn(global_state, data_x, data_y, n_max, rows, lane, step_keys, trip)
    -> (payload_weighted_sum_f32, weight_sum, metrics_sum)`` where
    ``data_x/data_y`` are device-resident stacks flattened on their first
    two axes (``[R * n_max, ...]``), ``rows`` maps schedule slot -> device
    row, ``lane`` is one lane's slice of the ``pack_lanes`` arrays and
    ``step_keys [L, 2]`` the pre-folded per-step PRNG keys. The lane
    trains its clients back-to-back: each client's final step flushes the
    weighted payload into the accumulator and resets carried state to the
    global model, so padded compute never executes.
    """
    optimizer = make_optimizer(cfg)

    def lane_update(global_state, data_x, data_y, n_max, rows, lane,
                    step_keys, trip):
        g_params, g_rest = _split_state(global_state)
        g_opt = optimizer.init(g_params)

        def batch_at(i):
            idx_b = jax.lax.dynamic_index_in_dim(
                lane["idx"], i, axis=0, keepdims=False)
            mask_b = jax.lax.dynamic_index_in_dim(
                lane["mask"], i, axis=0, keepdims=False)
            slot = jax.lax.dynamic_index_in_dim(
                lane["slot"], i, axis=0, keepdims=False)
            row = jnp.take(rows, slot)
            flat = row * n_max + idx_b
            return {"x": jnp.take(data_x, flat, axis=0),
                    "y": jnp.take(data_y, flat, axis=0),
                    "mask": mask_b}

        def grad_at(params, rest, batch, step_rng):
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            return jax.value_and_grad(loss_wrapper, has_aux=True)(params)

        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda: grad_at(
                g_params, g_rest, batch_at(0), step_keys[0]))[0][1][1])
        aux0 = {"n": jnp.float32(0), "steps": jnp.int32(0)}
        pay0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, jnp.float32),
            jax.eval_shape(payload_fn, global_state, global_state, aux0))

        def body(i, carry):
            params, rest, opt_state, pay, w, msum = carry
            batch = batch_at(i)
            step_rng = jax.lax.dynamic_index_in_dim(
                step_keys, i, axis=0, keepdims=False)
            (_, (new_state, metrics)), grads = grad_at(
                params, rest, batch, step_rng)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"]) > 0
            params, rest, opt_state = _tree_select(
                valid, (new_params, new_rest, new_opt),
                (params, rest, opt_state))
            msum = jax.tree.map(jnp.add, msum, metrics)

            # client boundary: flush weighted payload, reset to global
            f = jax.lax.dynamic_index_in_dim(
                lane["flush"], i, axis=0, keepdims=False)
            f_n = jax.lax.dynamic_index_in_dim(
                lane["flush_n"], i, axis=0, keepdims=False)
            f_steps = jax.lax.dynamic_index_in_dim(
                lane["flush_steps"], i, axis=0, keepdims=False)
            local_state = dict(rest)
            local_state["params"] = params
            payload = payload_fn(local_state, global_state,
                                 {"n": f_n,
                                  "steps": f_steps.astype(jnp.int32)})
            scale = f * f_n
            pay = jax.tree.map(
                lambda a, p: a + scale * p.astype(jnp.float32),
                pay, payload)
            w = w + scale
            params, rest, opt_state = _tree_select(
                f > 0, (g_params, g_rest, g_opt),
                (params, rest, opt_state))
            return (params, rest, opt_state, pay, w, msum)

        carry = (g_params, g_rest, g_opt, pay0, jnp.float32(0), metrics0)
        _, _, _, pay, w, msum = jax.lax.fori_loop(0, trip, body, carry)
        return pay, w, msum

    return lane_update


def make_packed_lane_update(spec: TrainSpec, cfg: ClientUpdateConfig,
                            payload_fn, n_lanes: int):
    """MXU-shaped variant of :func:`make_lane_update`: ALL lanes advance
    in one program per step, with the model's lane axis folded into
    channels by ``spec.lane_loss_builder`` (``models/lane_packed.py``)
    instead of ``jax.vmap`` over lane-stacked weights.

    Motivation (docs/PERFORMANCE.md): vmapped per-lane convs lower to
    ``feature_group_count=L`` grouped convs whose per-group K (the
    model's channel count, 16/32/64 for ResNet-56) underfills the MXU's
    128-wide systolic passes by 8x/4x/2x. The packed lowering merges
    lanes per group up to K=128. Everything outside the model forward --
    optimizer, payload, augmentation -- runs under a cheap elementwise
    ``jax.vmap`` over lanes, so per-lane semantics (valid-select, flush,
    divergent optimizer state) are bitwise those of the vmap path.

    Same signature/returns as the vmapped ``lane_update`` AFTER its
    round-level vmap: lanes arrays are ``[L, trip, ...]``, ``step_keys``
    ``[L, trip, 2]``, and the returns carry a leading lane axis.
    """
    optimizer = make_optimizer(cfg)
    del n_lanes  # the REAL lane count comes from the traced arrays:
    # pack_lanes may return fewer lanes than requested for small cohorts
    if spec.lane_loss_builder is None:
        raise ValueError(
            f"spec '{spec.name}' has no lane_loss_builder: the packed "
            "lane path (wave_mode=3) supports model families with a "
            "lane-packed lowering (models/lane_packed.py); use "
            "wave_mode=2 for the generic vmap lane path")

    def packed_update(global_state, data_x, data_y, n_max, rows, lanes,
                      step_keys, trip):
        L = lanes["idx"].shape[0]  # static at trace time
        lane_loss_fn = spec.lane_loss_builder(L)

        def _select(pred, new, old):
            """Per-lane select: ``pred [L]`` against leading-L leaves."""
            return jax.tree.map(
                lambda nw, od: jnp.where(
                    pred.reshape((L,) + (1,) * (nw.ndim - 1)), nw, od),
                new, old)

        g_params, g_rest = _split_state(global_state)
        stack = lambda t: jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (L,) + a.shape), t)
        sg_params, sg_rest = stack(g_params), stack(g_rest)
        # per-lane init (NOT init-of-stacked): leaves like Adam's count
        # must carry a lane axis so divergent lanes can be selected
        sg_opt = jax.vmap(optimizer.init)(sg_params)

        def batch_at(i):
            idx_b = jax.lax.dynamic_index_in_dim(
                lanes["idx"], i, axis=1, keepdims=False)  # [L, B]
            mask_b = jax.lax.dynamic_index_in_dim(
                lanes["mask"], i, axis=1, keepdims=False)
            slot = jax.lax.dynamic_index_in_dim(
                lanes["slot"], i, axis=1, keepdims=False)  # [L]
            row = jnp.take(rows, slot)
            flat = row[:, None] * n_max + idx_b  # [L, B]
            x = jnp.take(data_x, flat.reshape(-1), axis=0).reshape(
                flat.shape + data_x.shape[1:])
            y = jnp.take(data_y, flat.reshape(-1), axis=0).reshape(
                flat.shape + data_y.shape[1:])
            return {"x": x, "y": y, "mask": mask_b}

        def grad_at(params, rest, batch, step_rngs):
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = jax.vmap(
                    lambda xx, k: spec.augment_fn(
                        xx, jax.random.fold_in(k, 13)))(
                    batch["x"], step_rngs)

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return lane_loss_fn(state, batch, step_rngs, True)

            return jax.value_and_grad(loss_wrapper, has_aux=True)(params)

        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda: grad_at(
                sg_params, sg_rest, batch_at(0),
                step_keys[:, 0]))[0][1][1])
        aux0 = {"n": jnp.zeros((L,), jnp.float32),
                "steps": jnp.zeros((L,), jnp.int32)}
        vpayload = jax.vmap(payload_fn, in_axes=(0, None, 0))
        pay0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, jnp.float32),
            jax.eval_shape(vpayload, {**sg_rest, "params": sg_params},
                           global_state, aux0))

        def body(i, carry):
            params, rest, opt_state, pay, w, msum = carry
            batch = batch_at(i)
            step_rngs = jax.lax.dynamic_index_in_dim(
                step_keys, i, axis=1, keepdims=False)  # [L, 2]
            (_, (new_state, metrics)), grads = grad_at(
                params, rest, batch, step_rngs)
            updates, new_opt = jax.vmap(optimizer.update)(
                grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"], axis=1) > 0  # [L]
            params, rest, opt_state = _select(
                valid, (new_params, new_rest, new_opt),
                (params, rest, opt_state))
            msum = jax.tree.map(jnp.add, msum, metrics)

            f = jax.lax.dynamic_index_in_dim(
                lanes["flush"], i, axis=1, keepdims=False)  # [L]
            f_n = jax.lax.dynamic_index_in_dim(
                lanes["flush_n"], i, axis=1, keepdims=False)
            f_steps = jax.lax.dynamic_index_in_dim(
                lanes["flush_steps"], i, axis=1, keepdims=False)
            local_state = dict(rest)
            local_state["params"] = params
            payload = vpayload(local_state, global_state,
                               {"n": f_n, "steps": f_steps.astype(jnp.int32)})
            scale = f * f_n  # [L]
            pay = jax.tree.map(
                lambda a, p: a + scale.reshape(
                    (L,) + (1,) * (p.ndim - 1)) * p.astype(jnp.float32),
                pay, payload)
            w = w + scale
            params, rest, opt_state = _select(
                f > 0, (sg_params, sg_rest, sg_opt),
                (params, rest, opt_state))
            return (params, rest, opt_state, pay, w, msum)

        carry = (sg_params, sg_rest, sg_opt, pay0, jnp.zeros((L,),
                                                             jnp.float32),
                 metrics0)
        _, _, _, pay, w, msum = jax.lax.fori_loop(0, trip, body, carry)
        return pay, w, msum

    return packed_update


class LaneRunner:
    """Packed-lane execution: the WHOLE round as ONE jitted dispatch.

    ``pack_lanes`` lays the cohort's per-client step schedules end-to-end
    into K balanced lanes (LPT). Each lane's ``fori_loop`` trains clients
    back-to-back: at a client's final step the lane flushes the weighted
    payload into an on-device accumulator and resets its carried state to
    the global model, so no lane ever executes a padded fwd+bwd. Wall
    steps per round = max lane load ~= ceil(total_steps / K): strictly
    less straggle than size-sorted waves (``WaveRunner``), with a single
    program launch per round. RNG per client step is
    ``fold_in(client_key, local_step)`` with the same client keys as the
    flat paths, so lane, wave, and flat trajectories agree to float
    reassociation (tested in ``tests/test_engine.py``).

    Reference contrast: one torch process per client, rounds gated on the
    slowest process (``FedAVGAggregator.py:58-87``); here the scheduler
    is ~50 lines of host numpy and the chip never idles.
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig,
                 payload_fn=None, server_fn=None, n_lanes=8, packed=False):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.n_lanes = int(n_lanes or 8)
        self.packed = bool(packed)
        if self.packed:
            # MXU-shaped lowering: lane axis folded into channels by the
            # spec's lane_loss_builder (raises if the model family has
            # none) instead of vmap over lane-stacked weights
            packed_update = make_packed_lane_update(
                spec, cfg, self.payload_fn, self.n_lanes)
        else:
            lane_update = make_lane_update(spec, cfg, self.payload_fn)
        server_fn_ = self.server_fn

        @partial(jax.jit, donate_argnums=(0, 1))
        def round_fn(global_state, server_state, device_x, device_y, rows,
                     lanes, step_keys, trip, dtypes, rng):
            R, n_max = device_x.shape[0], device_x.shape[1]
            dx = device_x.reshape((R * n_max,) + device_x.shape[2:])
            dy = device_y.reshape((R * n_max,) + device_y.shape[2:])
            if self.packed:
                pay, w, msum = packed_update(
                    global_state, dx, dy, n_max, rows, lanes, step_keys,
                    trip)
            else:
                pay, w, msum = jax.vmap(
                    lane_update, in_axes=(None, None, None, None, None, 0,
                                          0, None))(
                    global_state, dx, dy, n_max, rows, lanes, step_keys,
                    trip)
            pay_sum = jax.tree.map(lambda x: jnp.sum(x, axis=0), pay)
            w_sum = jnp.sum(w)
            metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), msum)
            avg = jax.tree.map(
                lambda s, d: (s / jnp.maximum(w_sum, 1e-12)).astype(d.dtype),
                pay_sum, dtypes)
            new_global, new_server = server_fn_(global_state, avg,
                                                server_state, rng)
            return new_global, new_server, metrics_sum

        self._round_fn = round_fn
        self._fold_keys = fold_step_keys
        self._dtypes = None

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def run_round(self, global_state, server_state, device_data, ids, sched,
                  rng):
        """Same contract as :meth:`WaveRunner.run_round` (cohort ``ids``
        into ``device_data``, full ``pack_schedule`` output, round key);
        executes as one dispatch over ``n_lanes`` packed lanes."""
        import numpy as np

        from fedml_tpu.parallel.packing import pack_lanes

        C = len(np.asarray(sched["n"]))
        lanes = pack_lanes(sched, self.n_lanes)
        trip = jnp.int32(max(lanes.pop("trip"), 1))
        client_keys = jax.random.split(jax.random.fold_in(rng, 1), C)
        lane_arrays = {k: jnp.asarray(v) for k, v in lanes.items()
                       if k in ("idx", "mask", "slot", "flush", "flush_n",
                                "flush_steps")}
        step_keys = self._fold_keys(client_keys,
                                    jnp.asarray(lanes["slot"]),
                                    jnp.asarray(lanes["local_step"]))
        rows = jnp.asarray(np.asarray(ids, np.int32))
        with get_tracer().span("lanes", clients=int(C),
                               n_lanes=int(self.n_lanes), trip=int(trip)):
            new_global, new_server, metrics = self._round_fn(
                global_state, server_state, device_data["x"],
                device_data["y"], rows, lane_arrays, step_keys, trip,
                self._payload_dtypes(global_state),
                jax.random.fold_in(rng, 2))
        steps_pc = (np.asarray(sched["mask"]).sum(axis=2) > 0).sum(axis=1)
        aux = {"n": np.asarray(sched["n"], np.float32),
               "steps": steps_pc.astype(np.int64)}
        return new_global, new_server, {"aux": aux, "metrics": metrics}


class ShardedLaneRunner:
    """Packed lanes over a ``clients`` mesh: the multi-chip round as one
    SPMD dispatch with zero padded compute per shard.

    Client shards live in HBM sharded over the mesh's ``clients`` axis
    (each device owns a contiguous block of client rows); every mesh shard
    runs ITS resident cohort members as LPT-packed lanes (the
    :func:`make_lane_update` program), then the weighted payload sums meet
    in a ``psum`` over ICI and the server step runs replicated. This
    composes the single-chip lane design with the reference's multi-worker
    scaling story (SURVEY.md section 2.7/2.8): where the reference gates
    every round on its slowest client process and moves pickled
    state_dicts through MPI, here the only cross-chip traffic is one
    weighted-payload reduction.

    The fori_loop trip count is the max lane load across ALL shards
    (uniform SPMD control flow); shards with lighter loads run guarded
    no-op steps for the difference, so balance comes from placing clients
    on shards evenly (``FedAvgAPI`` places contiguous blocks; LDA skew
    within a block is absorbed by the in-shard LPT packing).
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig, mesh,
                 payload_fn=None, server_fn=None, n_lanes=8, packed=False):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.n_lanes = int(n_lanes or 8)
        self.mesh = mesh
        self.packed = bool(packed)
        if self.packed:
            # each shard runs ITS lanes through the MXU-shaped lowering
            # (models/lane_packed.py); the cross-chip psum is unchanged
            packed_update = make_packed_lane_update(
                spec, cfg, self.payload_fn, self.n_lanes)
        else:
            lane_update = make_lane_update(spec, cfg, self.payload_fn)
        server_fn_ = self.server_fn

        def shard_fn(global_state, server_state, dx, dy, rows, lanes,
                     step_keys, trip, dtypes, rng):
            # leading mesh axis arrives size-1 under shard_map: squeeze
            rows_l = rows[0]
            lanes_l = jax.tree.map(lambda a: a[0], lanes)
            keys_l = step_keys[0]
            R_local, n_max = dx.shape[0], dx.shape[1]
            dxf = dx.reshape((R_local * n_max,) + dx.shape[2:])
            dyf = dy.reshape((R_local * n_max,) + dy.shape[2:])
            if self.packed:
                pay, w, msum = packed_update(
                    global_state, dxf, dyf, n_max, rows_l, lanes_l,
                    keys_l, trip)
            else:
                pay, w, msum = jax.vmap(
                    lane_update,
                    in_axes=(None, None, None, None, None, 0, 0, None))(
                    global_state, dxf, dyf, n_max, rows_l, lanes_l, keys_l,
                    trip)
            pay_sum = jax.tree.map(
                lambda x: jax.lax.psum(jnp.sum(x, axis=0), CLIENT_AXIS),
                pay)
            w_sum = jax.lax.psum(jnp.sum(w), CLIENT_AXIS)
            metrics = jax.tree.map(
                lambda m: jax.lax.psum(jnp.sum(m, axis=0), CLIENT_AXIS),
                msum)
            avg = jax.tree.map(
                lambda s, d: (s / jnp.maximum(w_sum, 1e-12)).astype(d.dtype),
                pay_sum, dtypes)
            new_global, new_server = server_fn_(global_state, avg,
                                                server_state, rng)
            return new_global, new_server, metrics

        sharded = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(), P(), P(CLIENT_AXIS), P(CLIENT_AXIS),
                      P(CLIENT_AXIS), P(CLIENT_AXIS), P(CLIENT_AXIS),
                      P(), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False)
        self._round_fn = jax.jit(sharded, donate_argnums=(0, 1))
        self._fold_keys = fold_step_keys
        self._dtypes = None

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def run_round(self, global_state, server_state, device_data, ids, sched,
                  rng):
        """Same contract as :meth:`LaneRunner.run_round`; ``device_data``
        is SHARDED over the mesh's client axis (row blocks of size
        ``R / D``), and ``ids`` are global device rows."""
        import numpy as np

        from fedml_tpu.parallel.packing import pack_lanes

        mask = np.asarray(sched["mask"])
        C = mask.shape[0]
        D = self.mesh.shape[CLIENT_AXIS]
        R = int(device_data["x"].shape[0])
        assert R % D == 0, (R, D)
        block = R // D
        ids = np.asarray(ids, np.int64)
        K = self.n_lanes

        # split the cohort by owning shard; size lanes with the cheap
        # max-load query, pack arrays once per shard below
        from fedml_tpu.parallel.packing import lane_max_load

        steps_pc_all = (mask.sum(axis=2) > 0).sum(axis=1)
        per_shard = []
        l_needed = 1
        for d in range(D):
            members = np.nonzero((ids >= d * block)
                                 & (ids < (d + 1) * block))[0]
            sub = {k: np.asarray(sched[k])[members]
                   for k in ("idx", "mask", "n")}
            if len(members) == 0:
                sub = {"idx": np.zeros((1,) + mask.shape[1:], np.int32),
                       "mask": np.zeros((1,) + mask.shape[1:], np.float32),
                       "n": np.zeros((1,), np.float32)}
            else:
                l_needed = max(l_needed,
                               lane_max_load(steps_pc_all[members], K))
            per_shard.append((members, sub))

        # uniform allocation across shards (SPMD arrays must stack);
        # power-of-two bucket bounds recompiles across rounds
        L = 8
        while L < l_needed:
            L *= 2

        client_keys = jax.random.split(jax.random.fold_in(rng, 1), C)
        keys_np = np.asarray(client_keys)
        lane_stack, key_stack, row_stack, trips = [], [], [], []
        for d, (members, sub) in enumerate(per_shard):
            lanes = pack_lanes(sub, K, l_max=L)
            trips.append(lanes.pop("trip"))
            local_step = lanes.pop("local_step")
            k_sub = lanes["idx"].shape[0]
            if k_sub < K:  # pack_lanes clamps K to the member count;
                # pad with inert zero lanes so shards stack uniformly
                lanes = {k: np.concatenate(
                    [v, np.zeros((K - k_sub,) + v.shape[1:], v.dtype)])
                    for k, v in lanes.items()}
                local_step = np.concatenate(
                    [local_step,
                     np.zeros((K - k_sub,) + local_step.shape[1:],
                              local_step.dtype)])
            # slot -> LOCAL device row for this shard's member list
            rows_local = np.zeros((max(block, 1),), np.int32)
            if len(members):
                rows_local[:len(members)] = ids[members] - d * block
                member_keys = keys_np[members]
            else:
                member_keys = keys_np[:1]
            lane_stack.append(lanes)
            key_stack.append(self._fold_keys(
                jnp.asarray(member_keys), jnp.asarray(lanes["slot"]),
                jnp.asarray(local_step)))
            row_stack.append(rows_local)
        lanes_all = jax.tree.map(
            lambda *xs: jnp.asarray(np.stack(xs)), *lane_stack)
        keys_all = jnp.stack(key_stack)
        rows_all = jnp.asarray(np.stack(row_stack))
        trip = jnp.int32(max(max(trips), 1))

        with get_tracer().span("sharded-lanes", clients=int(C),
                               shards=int(D), trip=int(max(max(trips), 1))):
            new_global, new_server, metrics = self._round_fn(
                global_state, server_state, device_data["x"],
                device_data["y"], rows_all, lanes_all, keys_all, trip,
                self._payload_dtypes(global_state),
                jax.random.fold_in(rng, 2))
        steps_pc = (mask.sum(axis=2) > 0).sum(axis=1)
        aux = {"n": np.asarray(sched["n"], np.float32),
               "steps": steps_pc.astype(np.int64)}
        return new_global, new_server, {"aux": aux, "metrics": metrics}


def make_indexed_sim_round(spec: TrainSpec, cfg: ClientUpdateConfig,
                           payload_fn=None, server_fn=None,
                           client_chunk=None):
    """Single-chip round over device-resident data + index schedules.

    ``fn(global_state, server_state, device_data, sched, rng)`` with
    ``device_data`` leading axis = cohort clients. ``client_chunk`` bounds
    peak activation memory: clients run in sequential waves of ``chunk``
    (``lax.map`` outer, ``vmap`` inner) instead of all at once -- the knob
    that lets 32-client ResNet cohorts fit one chip's HBM.
    """
    client_update = make_indexed_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    @partial(jax.jit, donate_argnums=(0, 1))
    def round_fn(global_state, server_state, device_data, sched, rng):
        C = sched["mask"].shape[0]
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        server_rng = jax.random.fold_in(rng, 2)

        def run(d, s, r):
            return jax.vmap(client_update, in_axes=(None, 0, 0, 0))(
                global_state, d, s, r)

        chunk = client_chunk
        if chunk is not None and chunk < C:
            # pad the cohort to a chunk multiple with fully-masked dummy
            # clients (the shared zero_pad_leading invariant) so the
            # memory knob works for any cohort size
            pad = (-C) % chunk
            if pad:
                device_data = zero_pad_leading(device_data, pad, jnp)
                sched_p = zero_pad_leading(sched, pad, jnp)
                rngs_p = jnp.concatenate([rngs, rngs[:1].repeat(pad, 0)])
            else:
                sched_p, rngs_p = sched, rngs
            Cp = C + pad
            waves = Cp // chunk
            reshard = lambda a: a.reshape((waves, chunk) + a.shape[1:])
            dd = jax.tree.map(reshard, device_data)
            ss = jax.tree.map(reshard, sched_p)
            rr = reshard(rngs_p)
            local_states, aux, metrics = jax.lax.map(
                lambda args: run(*args), (dd, ss, rr))
            unshard = lambda a: a.reshape((Cp,) + a.shape[2:])[:C]
            local_states, aux, metrics = jax.tree.map(
                unshard, (local_states, aux, metrics))
        else:
            local_states, aux, metrics = run(device_data, sched, rngs)

        payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
            local_states, global_state, aux)
        avg_payload = pytree.tree_weighted_mean(payloads, aux["n"])
        new_global, new_server_state = server_fn(
            global_state, avg_payload, server_state, server_rng)
        return new_global, new_server_state, {"aux": aux, "metrics": metrics}

    return round_fn


def _default_payload(local_state, global_state, aux):
    return local_state


def _default_server(global_state, avg_payload, server_state, rng):
    return avg_payload, server_state


def payload_dtype_template(payload_fn, global_state):
    """Zero-scalar pytree carrying the payload's dtypes (the accumulators
    run in f32; the final average casts back through this template).
    Shared by every accumulate-then-normalize runner."""
    aux = {"n": jax.ShapeDtypeStruct((), jnp.float32),
           "steps": jax.ShapeDtypeStruct((), jnp.int32)}
    shapes = jax.eval_shape(payload_fn, global_state, global_state, aux)
    return jax.tree.map(lambda s: jnp.zeros((), s.dtype), shapes)


@jax.jit
def fold_step_keys(client_keys, slot, local_step):
    """Per-step PRNG keys for packed lanes:
    ``keys[k, i] = fold_in(client_keys[slot[k, i]], local_step[k, i])`` --
    the exact per-client-step derivation of the flat paths."""

    def one(s, t):
        return jax.random.fold_in(jnp.take(client_keys, s, axis=0), t)

    return jax.vmap(jax.vmap(one))(slot, local_step)


def make_sim_round(spec: TrainSpec, cfg: ClientUpdateConfig,
                   payload_fn=None, server_fn=None):
    """Single-chip round: clients vmapped over the cohort axis.

    ``fn(global_state, server_state, cohort_data, rng) ->
    (new_global, new_server_state, metrics)`` -- semantics of the reference
    standalone loop (``fedavg_api.py:40-115``) in one jitted call.
    """
    client_update = make_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    @partial(jax.jit, donate_argnums=(0, 1))
    def round_fn(global_state, server_state, cohort_data, rng):
        C = cohort_data["mask"].shape[0]
        # identical rng derivation as make_sharded_round so the two placements
        # produce bit-identical trajectories for stochastic models too
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        server_rng = jax.random.fold_in(rng, 2)
        local_states, aux, metrics = jax.vmap(
            client_update, in_axes=(None, 0, 0))(global_state, cohort_data, rngs)
        payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
            local_states, global_state, aux)
        avg_payload = pytree.tree_weighted_mean(payloads, aux["n"])
        new_global, new_server_state = server_fn(
            global_state, avg_payload, server_state, server_rng)
        return new_global, new_server_state, {"aux": aux, "metrics": metrics}

    return round_fn


def make_sharded_round(spec: TrainSpec, cfg: ClientUpdateConfig, mesh,
                       payload_fn=None, server_fn=None):
    """Pod-scale round: cohort sharded over the ``clients`` mesh axis.

    Each shard trains ``C / n_shards`` clients (vmapped locally), then the
    weighted average runs as ``psum`` collectives over ICI -- the TPU-native
    replacement for MPISendThread + CPU aggregation (reference
    ``mpi/com_manager.py:36-79`` + ``FedAVGAggregator.py:58-87``).
    Works on any mesh size including 1x1, so the same code path serves
    single-chip runs and pod slices.
    """
    client_update = make_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    def shard_fn(global_state, server_state, cohort_data, rng):
        # leading axis of cohort_data here is the *local* client count C/D
        local_states, aux, metrics = jax.vmap(
            client_update, in_axes=(None, 0, 0))(
                global_state, cohort_data, cohort_data["rngs"])
        payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
            local_states, global_state, aux)
        w = aux["n"].astype(jnp.float32)
        local_sum = jax.tree.map(
            lambda x: jnp.tensordot(w, x.astype(jnp.float32), axes=(0, 0)), payloads)
        total = jnp.maximum(jax.lax.psum(jnp.sum(w), CLIENT_AXIS), 1e-12)
        avg_payload = jax.tree.map(
            lambda x, t: (jax.lax.psum(x, CLIENT_AXIS) / total).astype(t.dtype),
            local_sum, jax.tree.map(lambda x: x[0], payloads))
        new_global, new_server_state = server_fn(
            global_state, avg_payload, server_state, rng)
        return new_global, new_server_state, {"aux": aux, "metrics": metrics}

    sharded = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(CLIENT_AXIS), P()),
        out_specs=(P(), P(), P(CLIENT_AXIS)),
        check_vma=False)

    @partial(jax.jit, donate_argnums=(0, 1))
    def round_fn(global_state, server_state, cohort_data, rng):
        C = cohort_data["mask"].shape[0]
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        data = dict(cohort_data)
        data["rngs"] = rngs
        return sharded(global_state, server_state, data,
                       jax.random.fold_in(rng, 2))

    return round_fn


def make_eval_fn(spec: TrainSpec):
    """Jitted evaluation over packed masked batches (``pack_eval`` output).
    Returns summed metric dict; divide by counts on host. Mirrors the
    reference eval protocol (``FedAVGAggregator.py:99-163``) with the model
    kept on device."""

    @jax.jit
    def eval_fn(state, data):
        def step(carry, batch):
            m = spec.metrics_fn(state, batch)
            return carry, m

        _, ms = jax.lax.scan(step, 0, {k: data[k] for k in ("x", "y", "mask")})
        return jax.tree.map(lambda x: jnp.sum(x, axis=0), ms)

    return eval_fn
