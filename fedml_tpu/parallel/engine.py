"""The federated round engine: one XLA program per round.

Reference behavior being replaced (SURVEY.md section 3.1): the server unicasts
pickled state_dicts to N client processes, each runs E epochs of local SGD,
sends weights back, and the server loops over state_dict keys on CPU.  Here
the entire round --

    per-client local-epochs ``lax.scan``  ->  weighted aggregation  ->  server step

-- is a single jitted function. Client parallelism is ``vmap`` on one chip
(standalone simulation, reference ``fedml_api/standalone/fedavg``) or
``shard_map`` over a ``clients`` mesh axis (distributed, reference
``fedml_api/distributed/fedavg``) with the weighted average as ``psum`` over
ICI. Both placements share the same ``client_update`` and the same
aggregator hooks, so every FL algorithm written against this engine runs in
both paradigms -- the reference needed two separate implementations per
algorithm (sections 2.2 vs 2.3).

Aggregator hooks (see ``fedml_tpu.algorithms``):
  payload_fn(local_state, global_state, aux) -> payload pytree
      per-client transform before averaging (identity for FedAvg, norm-clip
      for robust FedAvg, normalized delta for FedNova).
  server_fn(global_state, avg_payload, server_state, rng) -> (new_global, new_server_state)
      global update from the weighted-average payload (identity for FedAvg,
      optimizer step on the pseudo-gradient for FedOpt).

Consumers reach these round factories through
``RoundProgram.compile_sim`` / ``compile_bucketed``
(:mod:`fedml_tpu.program.sim`): the program object carries the
cohort/aggregation/codec policy and this module is its jit lowering --
the distributed control plane lowers the SAME program host-side via
``program.host_view()`` (docs/PROGRAM.md).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from fedml_tpu.core import pytree
from fedml_tpu.core.sharding import shard_map
from fedml_tpu.core.trainer import TrainSpec
from fedml_tpu.observability.costmodel import get_cost_model, program_cost
from fedml_tpu.observability.tracing import get_tracer
from fedml_tpu.parallel.mesh import CLIENT_AXIS, zero_pad_leading


@dataclasses.dataclass(frozen=True)
class ClientUpdateConfig:
    """Local-training hyperparameters (reference flags
    ``--client_optimizer --lr --wd``, ``main_fedavg.py:46-105``; optimizer
    construction parity with ``MyModelTrainer.py:25-31`` -- plain SGD or
    Adam(amsgrad) with weight decay, fresh optimizer state every round)."""
    optimizer: str = "sgd"
    lr: float = 0.03
    weight_decay: float = 0.0
    momentum: float = 0.0
    grad_clip: Optional[float] = None  # FedNAS clips local grads at 5.0


def make_optimizer(cfg: ClientUpdateConfig) -> optax.GradientTransformation:
    txs = []
    if cfg.grad_clip:
        txs.append(optax.clip_by_global_norm(cfg.grad_clip))
    if cfg.optimizer == "sgd":
        # torch.optim.SGD couples weight decay into the gradient
        if cfg.weight_decay:
            txs.append(optax.add_decayed_weights(cfg.weight_decay))
        txs.append(optax.sgd(cfg.lr, momentum=cfg.momentum or None))
    elif cfg.optimizer == "adam":
        # reference uses Adam(amsgrad=True, wd) -- MyModelTrainer.py:29-31;
        # torch couples wd into the gradient BEFORE the Adam statistics
        if cfg.weight_decay:
            txs.append(optax.add_decayed_weights(cfg.weight_decay))
        txs.append(optax.amsgrad(cfg.lr))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer}")
    return optax.chain(*txs)


def _split_state(state):
    params = state["params"]
    rest = {k: v for k, v in state.items() if k != "params"}
    return params, rest


def _tree_select(pred, new, old):
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), new, old)


def make_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Build the jittable per-client local-training function.

    Returns ``fn(global_state, client_data, rng) -> (local_state, aux)`` where
    ``client_data`` is one client's slice of a packed cohort
    (``x [S,B,...], y [S,B,...], mask [S,B], n []``) and ``aux`` carries the
    true sample count ``n`` and executed step count ``steps`` (FedNova's tau).
    Fully-masked (padded) steps leave all carried state untouched.
    """
    optimizer = make_optimizer(cfg)

    def client_update(global_state, client_data, rng):
        params, rest = _split_state(global_state)
        opt_state = optimizer.init(params)
        S = client_data["mask"].shape[0]

        def step(carry, xs):
            params, rest, opt_state = carry
            batch, step_idx = xs
            step_rng = jax.random.fold_in(rng, step_idx)
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            (loss, (new_state, metrics)), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(params)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"]) > 0
            new_carry = _tree_select(valid, (new_params, new_rest, new_opt),
                                     (params, rest, opt_state))
            return new_carry, metrics

        batches = {k: client_data[k] for k in ("x", "y", "mask")}
        (params, rest, _), metrics = jax.lax.scan(
            step, (params, rest, opt_state), (batches, jnp.arange(S)))
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(client_data["mask"] > 0, axis=-1))
        aux = {"n": client_data["n"], "steps": steps_done}
        # metrics leaves are [S, ...] per-step sums; padded steps contributed 0
        metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
        return local_state, aux, metrics_sum

    return client_update


def make_indexed_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Per-client local training over DEVICE-RESIDENT data.

    ``fn(global_state, data, sched, rng)`` where ``data`` is the client's
    full padded shard ``{"x": [n_max, ...], "y": [n_max, ...]}`` living in
    HBM and ``sched`` is a host-built index schedule ``{"idx": [S, B] int32,
    "mask": [S, B], "n": []}``. Each scan step *gathers* its batch on device
    (``jnp.take``), so the host stages bytes once per run instead of
    ``epochs x dataset`` copies per round -- the fix for SURVEY.md section 7
    hard part #2 (client-state swap without stalling).
    """
    optimizer = make_optimizer(cfg)

    def client_update(global_state, data, sched, rng):
        params, rest = _split_state(global_state)
        opt_state = optimizer.init(params)
        S = sched["mask"].shape[0]

        def step(carry, xs):
            params, rest, opt_state = carry
            idx_b, mask_b, step_idx = xs
            batch = {"x": jnp.take(data["x"], idx_b, axis=0),
                     "y": jnp.take(data["y"], idx_b, axis=0),
                     "mask": mask_b}
            step_rng = jax.random.fold_in(rng, step_idx)
            if spec.augment_fn is not None:
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            (loss, (new_state, metrics)), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(params)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(mask_b) > 0
            new_carry = _tree_select(valid, (new_params, new_rest, new_opt),
                                     (params, rest, opt_state))
            return new_carry, metrics

        (params, rest, _), metrics = jax.lax.scan(
            step, (params, rest, opt_state),
            (sched["idx"], sched["mask"], jnp.arange(S)))
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(sched["mask"] > 0, axis=-1))
        aux = {"n": sched["n"], "steps": steps_done}
        metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
        return local_state, aux, metrics_sum

    return client_update


def _make_trip_loop_core(spec: TrainSpec, cfg: ClientUpdateConfig):
    """THE dynamic-trip training loop, shared by every variant that runs
    exactly ``trip`` (traced-scalar) steps: grad + optimizer step +
    masked valid-select + running metric sums. The variants
    (:func:`make_loop_client_update` over device-resident data + index
    schedules, :func:`make_streamed_client_update` over pre-gathered
    chunk batches) differ ONLY in their ``batch_at`` -- fixes to
    masking, augmentation RNG, or optimizer semantics land here once.

    Returns ``run(global_state, batch_at, trip, rng) ->
    (params, rest, metrics_sum)``.
    """
    optimizer = make_optimizer(cfg)

    def run(global_state, batch_at, trip, rng):
        params, rest = _split_state(global_state)
        opt_state = optimizer.init(params)

        def grad_at(params, rest, batch, step_rng):
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            return jax.value_and_grad(loss_wrapper, has_aux=True)(params)

        # metric-structure discovery: abstract-eval one step, carry zeros
        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(
                lambda: grad_at(params, rest, batch_at(0), rng))[0][1][1])

        def body(i, carry):
            params, rest, opt_state, msum = carry
            batch = batch_at(i)
            step_rng = jax.random.fold_in(rng, i)
            (_, (new_state, metrics)), grads = grad_at(
                params, rest, batch, step_rng)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"]) > 0
            params, rest, opt_state = _tree_select(
                valid, (new_params, new_rest, new_opt),
                (params, rest, opt_state))
            msum = jax.tree.map(jnp.add, msum, metrics)
            return (params, rest, opt_state, msum)

        params, rest, _, msum = jax.lax.fori_loop(
            0, trip, body, (params, rest, opt_state, metrics0))
        return params, rest, msum

    return run


def make_loop_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Per-client local training as a ``fori_loop`` with a DYNAMIC trip count.

    ``fn(global_state, data, sched, steps, rng) -> (local_state, aux,
    metrics_sum)``. Unlike :func:`make_indexed_client_update`'s fixed-length
    ``scan``, the step loop runs exactly ``steps`` iterations where ``steps``
    is a *traced scalar* -- so one compiled program serves every wave length,
    and steps past a wave's true maximum are never executed at all (instead
    of executing fully-masked fwd+bwd no-ops). Metrics accumulate as running
    sums in the carry; schedule rows are fetched with ``dynamic_index_in_dim``.
    """
    run = _make_trip_loop_core(spec, cfg)

    def client_update(global_state, data, sched, steps, rng):
        def batch_at(i):
            idx_b = jax.lax.dynamic_index_in_dim(
                sched["idx"], i, axis=0, keepdims=False)
            mask_b = jax.lax.dynamic_index_in_dim(
                sched["mask"], i, axis=0, keepdims=False)
            return {"x": jnp.take(data["x"], idx_b, axis=0),
                    "y": jnp.take(data["y"], idx_b, axis=0),
                    "mask": mask_b}

        params, rest, msum = run(global_state, batch_at, steps, rng)
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(sched["mask"] > 0, axis=-1))
        aux = {"n": sched["n"], "steps": steps_done}
        return local_state, aux, msum

    return client_update


def make_streamed_client_update(spec: TrainSpec, cfg: ClientUpdateConfig):
    """Per-client local training over PRE-GATHERED batch arrays with a
    dynamic trip count -- the bucketed-streaming unit.

    ``fn(global_state, batches, n, trip, rng) -> (local_state, aux,
    metrics_sum)`` where ``batches`` is ``{"x": [S, B, ...], "y":
    [S, B, ...], "mask": [S, B]}`` staged per chunk (no device-resident
    dataset -- the cohort axis is unbounded) and ``trip`` is a *traced*
    scalar: the loop executes exactly ``trip`` steps, so steps past a
    chunk's true maximum are never run even though the array shape is
    padded to the bucket edge. Fully-masked steps inside the trip are
    guarded no-ops (same valid-select as every other update variant --
    the training loop itself is :func:`_make_trip_loop_core`).
    """
    run = _make_trip_loop_core(spec, cfg)

    def client_update(global_state, batches, n, trip, rng):
        def batch_at(i):
            return {k: jax.lax.dynamic_index_in_dim(
                        batches[k], i, axis=0, keepdims=False)
                    for k in ("x", "y", "mask")}

        params, rest, msum = run(global_state, batch_at, trip, rng)
        local_state = dict(rest)
        local_state["params"] = params
        steps_done = jnp.sum(jnp.any(batches["mask"] > 0, axis=-1))
        aux = {"n": n, "steps": steps_done}
        return local_state, aux, msum

    return client_update


class BucketedStreamRunner:
    """Bucketed ragged streaming: one chip, an UNBOUNDED cohort axis.

    The device-resident runners cap the cohort at what fits HBM and pad
    every client's schedule to the cohort max -- both walls at population
    scale (the paper's premise is O(10^4-10^6) non-IID clients with
    ragged sample counts per round). This runner removes both:

    - **Bucketing bounds padded compute.** The cohort is sorted ASCENDING
      by local step count and cut into fixed-size chunks; each chunk's
      schedule pads to the smallest GEOMETRIC edge covering it
      (``packing.parse_bucket_edges`` -- the compiled-shape anchor) while
      the dispatch's ``fori_loop`` trip count is the chunk's true maximum
      (a traced scalar), so steps past it never execute at all. Sorted
      neighbors make chunks near-homogeneous: executed-step waste is the
      sorted-adjacency slack (~0%, LPT-grade), and the edge only bounds
      the *allocated* shape. Fastest-first dispatch also mirrors a real
      async population's report order, so the staleness the async fold
      sees is honest.
    - **Streaming bounds memory.** Each dispatch stages one chunk's
      batches host->device (``packing.gather_batches``) and returns only
      the chunk's weighted payload SUM -- O(client_chunk) data and O(1)
      model state on device, regardless of cohort size. The per-chunk
      partials fold on host in float64 (the
      ``resilience.policy.fold_entries_fp64`` canonical fold) and one
      jitted ``advance_fn`` applies the server update.
    - **One compiled program per bucket shape**, pinned: ``trip`` is
      traced and every chunk of a bucket shares the edge-padded shape, so
      steady-state retraces are zero and ``compiled_shapes()`` equals the
      number of non-empty buckets (asserted in CI).

    Async composition: pass a ``resilience.async_agg.BufferedAggregator``
    and the stream folds chunk partials through it instead -- up to
    ``async_window`` chunks stay in flight (the simulated client
    concurrency), every ``buffer_k`` folded clients flush a server update
    MID-ROUND, and chunks dispatched before a flush fold in staleness-
    discounted. With an unbounded buffer and decay 0 this reduces to the
    synchronous fold bit-for-bit (the CI oracle).

    Streaming-EF (``compressor=``): the chunk program additionally runs
    the client->server half of the wire per lane -- compress the local
    update delta plus the client's error-feedback residual, reconstruct
    the server's view, and aggregate the RECONSTRUCTED states -- so the
    payload partial sums are exactly what a real compressed transport
    would deliver. Residuals are gathered/scattered by STABLE client id
    through a ``compression.ResidualStore`` handed to :meth:`run_round`
    (dense device rows when the population fits, lazy host spill
    beyond), the residual arrays share the chunk's ONE compiled shape
    per bucket edge (``[client_chunk, ...]`` rows -- the compressor
    changes no shape), and the scatter-back happens at the fold point,
    so the dense path keeps the ``async_window`` pipeline fully
    asynchronous. Zero steady-state retraces and ``compiled_shapes() ==
    buckets_used`` hold exactly as in the plain path (CI-gated).
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig,
                 payload_fn=None, server_fn=None, client_chunk=256,
                 batch_size=32, epochs=1, edges=(8,), step_bucket=8,
                 compressor=None):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.client_chunk = max(1, int(client_chunk))
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.edges = sorted(int(e) for e in edges)
        self.step_bucket = int(step_bucket)
        self.compressor = compressor
        client_update = make_streamed_client_update(spec, cfg)
        payload_fn_ = self.payload_fn
        server_fn_ = self.server_fn

        def _aggregate(global_state, local_states, aux, metrics):
            payloads = jax.vmap(payload_fn_, in_axes=(0, None, 0))(
                local_states, global_state, aux)
            w = aux["n"].astype(jnp.float32)
            pay_sum = jax.tree.map(
                lambda x: jnp.tensordot(w, x.astype(jnp.float32),
                                        axes=(0, 0)),
                payloads)
            metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0),
                                       metrics)
            return pay_sum, jnp.sum(w), metrics_sum

        if compressor is None:
            @jax.jit
            def chunk_fn(global_state, batches, ns, trip, rngs):
                local_states, aux, metrics = jax.vmap(
                    client_update, in_axes=(None, 0, 0, None, 0))(
                        global_state, batches, ns, trip, rngs)
                return _aggregate(global_state, local_states, aux, metrics)
        else:
            from fedml_tpu.compression.compressors import ErrorFeedback
            ef = ErrorFeedback(compressor)

            @jax.jit
            def chunk_fn(global_state, batches, ns, trip, rngs,
                         residuals, crngs):
                local_states, aux, metrics = jax.vmap(
                    client_update, in_axes=(None, 0, 0, None, 0))(
                        global_state, batches, ns, trip, rngs)

                def compress_one(local_state, residual, crng):
                    # the client->server wire half, per lane: EF-compress
                    # the update delta, aggregate the server's RECON view
                    # (make_compressed_sim_round's exact semantics,
                    # streamed); only "params" is lossy -- batch_stats
                    # and other state average at full fidelity
                    delta = pytree.tree_sub(local_state["params"],
                                            global_state["params"])
                    _, dec, new_residual = ef.step(
                        delta, residual, global_state["params"], crng)
                    recon = dict(local_state)
                    recon["params"] = pytree.tree_add(
                        global_state["params"], dec)
                    return recon, new_residual

                with jax.named_scope("ef-compress"):
                    recon_states, new_residuals = jax.vmap(compress_one)(
                        local_states, residuals, crngs)
                pay_sum, w_sum, metrics_sum = _aggregate(
                    global_state, recon_states, aux, metrics)
                return pay_sum, w_sum, metrics_sum, new_residuals

        @partial(jax.jit, donate_argnums=(0, 1))
        def advance_fn(global_state, server_state, avg_payload, rng):
            return server_fn_(global_state, avg_payload, server_state, rng)

        self._chunk_fn = chunk_fn
        self._advance_fn = advance_fn
        self._dtypes = None
        # per-bucket-edge ProgramCost (or None for "probed, no cost
        # analysis"), populated lazily ONLY while a CostModel is armed;
        # the AOT probe compiles once per edge (warm-up round) and never
        # touches the jit dispatch cache, so compiled_shapes() and the
        # zero-steady-state-retrace gates stay honest
        self._edge_costs = {}

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def compiled_shapes(self) -> int:
        """Distinct compiled chunk programs (should equal the number of
        non-empty buckets ever dispatched -- the retrace-audit anchor)."""
        try:
            return int(self._chunk_fn._cache_size())
        except AttributeError:  # older jax: no cache introspection
            return -1

    def run_round(self, global_state, server_state, datasets, rng,
                  data_rng=None, aggregator=None, async_window=4,
                  client_ids=None, residual_store=None):
        """One federated round over ``datasets`` (the cohort's raw client
        shards, list of ``{"x", "y"}``), streamed bucket by bucket.

        ``aggregator`` (optional ``BufferedAggregator``) switches the
        host-side fold to buffered-async; otherwise the partials fold
        synchronously. With a ``compressor`` armed, ``residual_store``
        (a ``compression.ResidualStore``) carries each client's EF
        residual across the rounds it is sampled into, keyed by
        ``client_ids`` (stable ids aligned with ``datasets``; defaults
        to cohort ordinals for store-owning callers like the direct
        tests). Returns ``(new_global, new_server_state, info)`` with
        ``info["bucket"]`` (waste accounting) and ``info["async"]``
        (buffer counters) next to the usual ``aux``/``metrics``.
        """
        import numpy as np
        from collections import deque

        from fedml_tpu.parallel.packing import (
            _steps_for, bucket_edge_for, gather_batches, pack_schedule)

        data_rng = data_rng or np.random.default_rng(0)
        C = len(datasets)
        if C == 0:
            raise ValueError("bucketed round over an empty cohort")
        if self.compressor is not None and residual_store is None:
            raise ValueError(
                "streaming-EF needs a residual_store: the error-feedback "
                "accumulator is keyed by stable client id ACROSS rounds "
                "(compression.ResidualStore; FedAvgAPI owns one)")
        ns = [len(d["y"]) for d in datasets]
        if sum(ns) == 0:
            raise ValueError("bucketed round: every client shard is empty")
        if self.batch_size in (-1, 0):
            # full-batch convention: resolve ONCE (first cohort seen) and
            # pin it -- a per-cohort B would change the [C, S, B] compiled
            # shape whenever a re-sampled cohort's largest shard differs,
            # breaking the zero-steady-state-retrace invariant. FedAvgAPI
            # resolves from the POPULATION max before construction.
            self.batch_size = max(1, max(ns))
        bs = self.batch_size
        steps_pc = np.asarray(
            [_steps_for(max(n, 1), bs, self.epochs) for n in ns], np.int64)
        bucket_edge_for(steps_pc.max(), self.edges)  # top-edge guard
        client_keys = np.asarray(
            jax.random.split(jax.random.fold_in(rng, 1), C))
        dtypes = self._payload_dtypes(global_state)
        flush_rng = jax.random.fold_in(rng, 2)
        comp_keys = None
        if self.compressor is not None:
            # fold 3 is the compression stream -- the same derivation
            # rule as make_compressed_sim_round, per stable cohort slot
            comp_keys = np.asarray(
                jax.random.split(jax.random.fold_in(rng, 3), C))
            if client_ids is None:
                client_ids = list(range(C))

        gs, ss = global_state, server_state
        cm = get_cost_model()  # one global read when attribution is off
        flushes = 0
        metrics_acc = None
        # sync path: incremental canonical fold. Entries are consumed in
        # ordinal (= sorted-key) order, so accumulating here is bitwise
        # fold_entries_fp64 over the same entries -- with O(1 model) host
        # memory instead of retaining every chunk payload to round end
        sync_acc = {"num": None, "w": 0.0}
        inflight = deque()
        exec_steps = 0
        per_bucket = []
        tracer = get_tracer()

        def apply_avg(avg, f):
            # avg: f32 numpy pytree from the canonical fold; cast through
            # the payload dtype template (accumulators run f32/f64, the
            # model may not) and run the donated server step
            nonlocal gs, ss
            avg_dev = jax.tree.map(
                lambda a, d: jnp.asarray(np.asarray(a), d.dtype), avg,
                dtypes)
            gs, ss = self._advance_fn(gs, ss, avg_dev,
                                      jax.random.fold_in(flush_rng, f))

        def fold_oldest():
            nonlocal flushes, metrics_acc
            ordinal, born, k_real, handles, scatter = inflight.popleft()
            if scatter is not None:
                # EF residual write-back, deferred to the fold point (the
                # documented sync point): the dense store's at[].set is
                # pure device work and keeps the pipeline asynchronous;
                # the sparse (host-spill) backing pays its np.asarray
                # sync here, where the chunk's outputs sync anyway
                ids, new_res = scatter
                residual_store.scatter(
                    ids, jax.tree.map(lambda x: x[:len(ids)], new_res))
            # FIRST host touch of this chunk's outputs: the device sync
            # point. Everything stays a device handle until here, so up
            # to async_window chunks genuinely overlap host packing/H2D
            # staging with device compute.
            pay = jax.tree.map(np.asarray, handles[0])
            w = float(np.asarray(handles[1]))
            m_host = jax.tree.map(
                lambda m: np.asarray(m, np.float64), handles[2])
            metrics_acc = m_host if metrics_acc is None else \
                jax.tree.map(np.add, metrics_acc, m_host)
            staleness = (aggregator.version - born) if aggregator else 0
            if aggregator is None:
                contrib = jax.tree.map(
                    lambda x: np.asarray(x, np.float64), pay)
                sync_acc["num"] = contrib if sync_acc["num"] is None \
                    else jax.tree.map(np.add, sync_acc["num"], contrib)
                sync_acc["w"] += w
                return
            aggregator.fold(ordinal, w, pay, staleness=staleness,
                            clients=k_real, preweighted=True)
            if aggregator.ready():
                res = aggregator.flush("buffer_k")
                apply_avg(res.params, flushes)
                flushes += 1

        # fastest-first streaming: the cohort is sorted ASCENDING by step
        # count and cut into chunks; each chunk's schedule is padded to
        # the smallest covering bucket edge (the compiled-shape anchor)
        # while its fori_loop trip is the chunk's true maximum. Sorted
        # neighbors make chunks near-homogeneous, so executed-step waste
        # is the sorted-adjacency slack (~0%, LPT-grade) -- and dispatch
        # order mirrors a real async population, whose fastest clients
        # report first (the staleness the async fold sees is honest).
        order = np.argsort(steps_pc, kind="stable")
        b_stats = {e: {"clients": 0, "chunks": 0, "executed_steps": 0,
                       "true_steps": 0} for e in self.edges}
        ordinal = 0
        for c0 in range(0, C, self.client_chunk):
            chunk = [int(i) for i in order[c0:c0 + self.client_chunk]]
            k = len(chunk)
            trip = int(steps_pc[chunk].max())
            edge = int(bucket_edge_for(trip, self.edges))
            sched = pack_schedule([ns[i] for i in chunk], bs, self.epochs,
                                  rng=data_rng, s_max=edge,
                                  step_bucket=self.step_bucket)
            xb, yb = gather_batches(datasets, sched, chunk)
            maskb = sched["mask"]
            n_arr = sched["n"]
            rngs = client_keys[chunk]
            if k < self.client_chunk:  # ragged final chunk: pad to the
                # bucket's ONE compiled shape with inert clients
                pad = self.client_chunk - k
                xb, yb, maskb, n_arr = zero_pad_leading(
                    (xb, yb, maskb, n_arr), pad)
                rngs = np.concatenate([rngs, rngs[:1].repeat(pad, 0)])
            born = aggregator.version if aggregator else 0
            batches_dev = {"x": jnp.asarray(xb), "y": jnp.asarray(yb),
                           "mask": jnp.asarray(maskb)}
            ns_dev, rngs_dev = jnp.asarray(n_arr), jnp.asarray(rngs)
            args = (gs, batches_dev, ns_dev, jnp.int32(trip), rngs_dev)
            ids = None
            if self.compressor is not None:
                # EF residual rows for this chunk, gathered by STABLE
                # client id; padded lanes carry zero rows that share the
                # bucket's one compiled shape and are sliced off before
                # the scatter-back (their updates are discarded)
                ids = [client_ids[i] for i in chunk]
                res = residual_store.gather(ids)
                crngs = comp_keys[chunk]
                if k < self.client_chunk:
                    pad = self.client_chunk - k
                    res = jax.tree.map(
                        lambda x: jnp.concatenate(
                            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]),
                        res)
                    crngs = np.concatenate(
                        [crngs, crngs[:1].repeat(pad, 0)])
                args = args + (res, jnp.asarray(crngs))
            with tracer.span("bucket-chunk", edge=edge, clients=int(k),
                             trip=trip):
                out = self._chunk_fn(*args)
            if self.compressor is None:
                pay_sum, w_sum, msum = out
                scatter = None
            else:
                pay_sum, w_sum, msum, new_res = out
                scatter = (ids, new_res)
            if cm is not None:
                if edge not in self._edge_costs:
                    # abstract AOT probe of this bucket shape's program
                    # (the dispatch above runs async meanwhile):
                    # ShapeDtypeStructs only, so the probe never holds
                    # or syncs device buffers
                    abst = lambda t: jax.tree.map(
                        lambda a: jax.ShapeDtypeStruct(
                            a.shape, a.dtype), t)
                    self._edge_costs[edge] = program_cost(
                        self._chunk_fn,
                        *(abst(a) if i != 3
                          else jax.ShapeDtypeStruct((), jnp.int32)
                          for i, a in enumerate(args)))
                # note() every time (setdefault-idempotent): a CostModel
                # armed AFTER the runner warmed its edge cache must
                # still collect the catalog
                cm.note(f"bucket_chunk_s{edge}", self._edge_costs[edge])
            inflight.append((ordinal, born, k, (pay_sum, w_sum, msum),
                             scatter))
            ordinal += 1
            st = b_stats[edge]
            st["clients"] += k
            st["chunks"] += 1
            # padded lanes of the (single) ragged final chunk run too --
            # the waste accounting counts every executed vmap lane
            st["executed_steps"] += trip * self.client_chunk
            st["true_steps"] += int(steps_pc[chunk].sum())
            exec_steps += trip * self.client_chunk
            while len(inflight) > max(1, int(async_window)):
                fold_oldest()
        flops_exec, flops_true, have_cost = 0.0, 0.0, False
        for e in self.edges:
            st = b_stats[e]
            row = {"edge": int(e), "skipped": int(st["chunks"] == 0), **st}
            pc = self._edge_costs.get(e)
            if pc is not None and st["chunks"]:
                # XLA cost analysis charges a dynamic-trip loop body
                # ONCE: program flops ~= one step across all client_chunk
                # lanes (+ the per-dispatch aggregation epilogue, which
                # step-dominated chunks amortize -- docs/OBSERVABILITY.md)
                per_lane_step = pc.flops / self.client_chunk
                row["flops_per_step"] = per_lane_step
                row["executed_flops"] = per_lane_step * st["executed_steps"]
                row["true_flops"] = per_lane_step * st["true_steps"]
                row["bytes_accessed"] = pc.bytes_accessed
                flops_exec += row["executed_flops"]
                flops_true += row["true_flops"]
                have_cost = True
            per_bucket.append(row)

        while inflight:
            fold_oldest()
        if aggregator is not None:
            if aggregator.depth:
                # round-boundary drain: whatever is buffered flushes even
                # below K (the stream is over; holding updates across
                # rounds would starve the last window)
                res = aggregator.flush("drain")
                apply_avg(res.params, flushes)
                flushes += 1
            async_info = aggregator.record()
            async_info["async/flushes_this_round"] = flushes
        else:
            total = sync_acc["w"]
            if sync_acc["num"] is None or total <= 0:
                raise ValueError("bucketed round folded zero weight "
                                 "(every cohort shard empty?)")
            avg = jax.tree.map(
                lambda x: (x / total).astype(np.float32), sync_acc["num"])
            apply_avg(avg, 0)
            flushes = 1
            async_info = None

        true_steps = int(steps_pc.sum())
        info = {
            "aux": {"n": np.asarray(ns, np.float32),
                    "steps": steps_pc.astype(np.int64)},
            "metrics": metrics_acc,
            "bucket": {
                "edges": list(self.edges),
                "buckets_used": sum(1 for b in per_bucket
                                    if not b["skipped"]),
                "clients": C, "chunks": ordinal,
                "executed_steps": int(exec_steps),
                "true_steps": true_steps,
                "waste_frac": round(1.0 - true_steps / max(exec_steps, 1),
                                    4),
                "per_bucket": per_bucket,
            },
        }
        if have_cost and flops_exec > 0:
            # padded waste in FLOPs, from the programs actually compiled
            # (not step counts): buckets missing a cost probe are
            # excluded from both numerator and denominator
            info["bucket"]["executed_flops"] = flops_exec
            info["bucket"]["true_flops"] = flops_true
            info["bucket"]["flops_waste_frac"] = round(
                1.0 - flops_true / flops_exec, 4)
            info["bucket"]["flops_source"] = "xla"
        if async_info is not None:
            info["async"] = async_info
        return gs, ss, info


class WaveRunner:
    """Size-sorted wave execution of a federated round over device-resident
    data -- the throughput path for single-chip cohorts.

    The flat ``make_indexed_sim_round`` pads every client to the cohort-max
    step count, so under a skewed LDA partition most clients burn most steps
    on fully-masked fwd+bwd no-ops. Here the cohort is sorted by true step
    count and dispatched in waves of ``client_chunk`` clients; each wave runs
    one jitted program whose ``fori_loop`` trip count is the *wave* maximum
    (a traced scalar -- no recompilation across waves or rounds). Weighted
    payload sums accumulate on device across waves; a final jitted step
    normalizes and applies ``server_fn``. Total executed steps drop from
    ``C x S_max`` to ``sum_w k x S_w`` -- the padding-waste fix for the
    reference's straggler problem (its MPI path simply blocks on the slowest
    client process, ``FedAVGAggregator.py:58-87``).

    Consumes the SAME ``pack_schedule`` output (same host-RNG draw) as the
    flat path, so switching paths never perturbs the data stream, and
    checkpoints resume across either.
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig,
                 payload_fn=None, server_fn=None, client_chunk=8):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.client_chunk = int(client_chunk or 8)
        client_update = make_loop_client_update(spec, cfg)
        payload_fn_ = self.payload_fn
        server_fn_ = self.server_fn

        @jax.jit
        def wave_fn(global_state, device_x, device_y, ids, sched, steps, rngs):
            data = {"x": jnp.take(device_x, ids, axis=0),
                    "y": jnp.take(device_y, ids, axis=0)}
            local_states, aux, metrics = jax.vmap(
                client_update, in_axes=(None, 0, 0, None, 0))(
                    global_state, data, sched, steps, rngs)
            payloads = jax.vmap(payload_fn_, in_axes=(0, None, 0))(
                local_states, global_state, aux)
            w = aux["n"].astype(jnp.float32)
            pay_sum = jax.tree.map(
                lambda x: jnp.tensordot(w, x.astype(jnp.float32), axes=(0, 0)),
                payloads)
            metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
            return pay_sum, jnp.sum(w), metrics_sum, aux

        @jax.jit
        def add_fn(a, b):
            return jax.tree.map(jnp.add, a, b)

        @jax.jit
        def finish_fn(global_state, server_state, pay_sum, w_sum, dtypes, rng):
            # weighted mean over the accumulated sums. NOTE: unlike
            # pytree.tree_weighted_mean there is no uniform fallback here --
            # an all-empty cohort (w_sum == 0) yields a zero payload, so
            # callers MUST fail fast on empty cohorts before dispatch
            # (FedAvgAPI.train_one_round raises; direct users take note)
            avg = jax.tree.map(
                lambda s, d: (s / jnp.maximum(w_sum, 1e-12)).astype(d.dtype),
                pay_sum, dtypes)
            return server_fn_(global_state, avg, server_state, rng)

        self._wave_fn = wave_fn
        self._add_fn = add_fn
        self._finish_fn = finish_fn
        self._dtypes = None

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def run_round(self, global_state, server_state, device_data, ids, sched,
                  rng):
        """One federated round.

        Args:
          device_data: ``{"x": [N_rows, ...], "y": [N_rows, ...]}`` full
            client shards resident in HBM (``stack_clients`` output).
          ids: cohort client rows into ``device_data`` (cohort order).
          sched: full packed schedule (``pack_schedule`` output, numpy,
            cohort order) -- ``{"idx" [C,S,B], "mask" [C,S,B], "n" [C]}``.
          rng: round PRNG key; per-client keys derive exactly as in the flat
            paths (``split(fold_in(rng, 1), C)`` indexed by cohort slot), so
            wave and flat trajectories agree to float reassociation.
        """
        import numpy as np

        mask = np.asarray(sched["mask"])
        C = mask.shape[0]
        steps_per_client = (mask.sum(axis=2) > 0).sum(axis=1).astype(np.int64)
        order = np.argsort(-steps_per_client, kind="stable")
        chunk = min(self.client_chunk, C)
        all_rngs = np.asarray(jax.random.split(jax.random.fold_in(rng, 1), C))
        ids = np.asarray(ids, np.int32)
        sched_idx = np.asarray(sched["idx"])
        sched_n = np.asarray(sched["n"], np.float32)

        acc = None
        wave_aux, wave_pos = [], []
        for w0 in range(0, C, chunk):
            pos = order[w0:w0 + chunk]
            k = len(pos)
            trip = int(steps_per_client[pos].max())
            w_idx, w_mask = sched_idx[pos], mask[pos]
            w_n, w_ids, w_rngs = sched_n[pos], ids[pos], all_rngs[pos]
            if k < chunk:  # pad the ragged last wave -> one stable jit shape
                pad = chunk - k
                w_idx, w_mask, w_n, w_ids = zero_pad_leading(
                    (w_idx, w_mask, w_n, w_ids), pad)
                w_rngs = np.concatenate([w_rngs, w_rngs[:1].repeat(pad, 0)])
            ws = {"idx": jnp.asarray(w_idx), "mask": jnp.asarray(w_mask),
                  "n": jnp.asarray(w_n)}
            # span measures dispatch (async): device time for the whole
            # round lands in the caller's end-of-round sync
            with get_tracer().span("wave", clients=int(k), trip=trip):
                pay_sum, w_sum, metrics_sum, aux = self._wave_fn(
                    global_state, device_data["x"], device_data["y"],
                    jnp.asarray(w_ids), ws, jnp.int32(trip),
                    jnp.asarray(w_rngs))
            part = (pay_sum, w_sum, metrics_sum)
            acc = part if acc is None else self._add_fn(acc, part)
            wave_aux.append(aux)
            wave_pos.append(pos)

        pay_sum, w_sum, metrics_sum = acc
        with get_tracer().span("server-update"):
            new_global, new_server_state = self._finish_fn(
                global_state, server_state, pay_sum, w_sum,
                self._payload_dtypes(global_state),
                jax.random.fold_in(rng, 2))

        # gather per-client aux back into cohort order (host, post-dispatch)
        aux_out = {"n": np.zeros(C, np.float32),
                   "steps": np.zeros(C, np.int64)}
        for pos, aux in zip(wave_pos, wave_aux):
            k = len(pos)
            aux_out["n"][pos] = np.asarray(aux["n"])[:k]
            aux_out["steps"][pos] = np.asarray(aux["steps"])[:k]
        return new_global, new_server_state, {"aux": aux_out,
                                              "metrics": metrics_sum}


def make_lane_update(spec: TrainSpec, cfg: ClientUpdateConfig, payload_fn):
    """Build the per-lane sequential-clients update (shared by
    :class:`LaneRunner` and :class:`ShardedLaneRunner`).

    ``fn(global_state, data_x, data_y, n_max, rows, lane, step_keys, trip)
    -> (payload_weighted_sum_f32, weight_sum, metrics_sum)`` where
    ``data_x/data_y`` are device-resident stacks flattened on their first
    two axes (``[R * n_max, ...]``), ``rows`` maps schedule slot -> device
    row, ``lane`` is one lane's slice of the ``pack_lanes`` arrays and
    ``step_keys [L, 2]`` the pre-folded per-step PRNG keys. The lane
    trains its clients back-to-back: each client's final step flushes the
    weighted payload into the accumulator and resets carried state to the
    global model, so padded compute never executes.
    """
    optimizer = make_optimizer(cfg)

    def lane_update(global_state, data_x, data_y, n_max, rows, lane,
                    step_keys, trip):
        g_params, g_rest = _split_state(global_state)
        g_opt = optimizer.init(g_params)

        def batch_at(i):
            idx_b = jax.lax.dynamic_index_in_dim(
                lane["idx"], i, axis=0, keepdims=False)
            mask_b = jax.lax.dynamic_index_in_dim(
                lane["mask"], i, axis=0, keepdims=False)
            slot = jax.lax.dynamic_index_in_dim(
                lane["slot"], i, axis=0, keepdims=False)
            row = jnp.take(rows, slot)
            flat = row * n_max + idx_b
            return {"x": jnp.take(data_x, flat, axis=0),
                    "y": jnp.take(data_y, flat, axis=0),
                    "mask": mask_b}

        def grad_at(params, rest, batch, step_rng):
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = spec.augment_fn(
                    batch["x"], jax.random.fold_in(step_rng, 13))

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return spec.loss_fn(state, batch, step_rng, True)

            return jax.value_and_grad(loss_wrapper, has_aux=True)(params)

        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda: grad_at(
                g_params, g_rest, batch_at(0), step_keys[0]))[0][1][1])
        aux0 = {"n": jnp.float32(0), "steps": jnp.int32(0)}
        pay0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, jnp.float32),
            jax.eval_shape(payload_fn, global_state, global_state, aux0))

        def body(i, carry):
            params, rest, opt_state, pay, w, msum = carry
            batch = batch_at(i)
            step_rng = jax.lax.dynamic_index_in_dim(
                step_keys, i, axis=0, keepdims=False)
            (_, (new_state, metrics)), grads = grad_at(
                params, rest, batch, step_rng)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"]) > 0
            params, rest, opt_state = _tree_select(
                valid, (new_params, new_rest, new_opt),
                (params, rest, opt_state))
            msum = jax.tree.map(jnp.add, msum, metrics)

            # client boundary: flush weighted payload, reset to global
            f = jax.lax.dynamic_index_in_dim(
                lane["flush"], i, axis=0, keepdims=False)
            f_n = jax.lax.dynamic_index_in_dim(
                lane["flush_n"], i, axis=0, keepdims=False)
            f_steps = jax.lax.dynamic_index_in_dim(
                lane["flush_steps"], i, axis=0, keepdims=False)
            local_state = dict(rest)
            local_state["params"] = params
            payload = payload_fn(local_state, global_state,
                                 {"n": f_n,
                                  "steps": f_steps.astype(jnp.int32)})
            scale = f * f_n
            pay = jax.tree.map(
                lambda a, p: a + scale * p.astype(jnp.float32),
                pay, payload)
            w = w + scale
            params, rest, opt_state = _tree_select(
                f > 0, (g_params, g_rest, g_opt),
                (params, rest, opt_state))
            return (params, rest, opt_state, pay, w, msum)

        carry = (g_params, g_rest, g_opt, pay0, jnp.float32(0), metrics0)
        _, _, _, pay, w, msum = jax.lax.fori_loop(0, trip, body, carry)
        return pay, w, msum

    return lane_update


def make_packed_lane_update(spec: TrainSpec, cfg: ClientUpdateConfig,
                            payload_fn, n_lanes: int):
    """MXU-shaped variant of :func:`make_lane_update`: ALL lanes advance
    in one program per step, with the model's lane axis folded into
    channels by ``spec.lane_loss_builder`` (``models/lane_packed.py``)
    instead of ``jax.vmap`` over lane-stacked weights.

    Motivation (docs/PERFORMANCE.md): vmapped per-lane convs lower to
    ``feature_group_count=L`` grouped convs whose per-group K (the
    model's channel count, 16/32/64 for ResNet-56) underfills the MXU's
    128-wide systolic passes by 8x/4x/2x. The packed lowering merges
    lanes per group up to K=128. Everything outside the model forward --
    optimizer, payload, augmentation -- runs under a cheap elementwise
    ``jax.vmap`` over lanes, so per-lane semantics (valid-select, flush,
    divergent optimizer state) are bitwise those of the vmap path.

    Same signature/returns as the vmapped ``lane_update`` AFTER its
    round-level vmap: lanes arrays are ``[L, trip, ...]``, ``step_keys``
    ``[L, trip, 2]``, and the returns carry a leading lane axis.
    """
    optimizer = make_optimizer(cfg)
    del n_lanes  # the REAL lane count comes from the traced arrays:
    # pack_lanes may return fewer lanes than requested for small cohorts
    if spec.lane_loss_builder is None:
        raise ValueError(
            f"spec '{spec.name}' has no lane_loss_builder: the packed "
            "lane path (wave_mode=3) supports model families with a "
            "lane-packed lowering (models/lane_packed.py); use "
            "wave_mode=2 for the generic vmap lane path")

    def packed_update(global_state, data_x, data_y, n_max, rows, lanes,
                      step_keys, trip):
        L = lanes["idx"].shape[0]  # static at trace time
        lane_loss_fn = spec.lane_loss_builder(L)

        def _select(pred, new, old):
            """Per-lane select: ``pred [L]`` against leading-L leaves."""
            return jax.tree.map(
                lambda nw, od: jnp.where(
                    pred.reshape((L,) + (1,) * (nw.ndim - 1)), nw, od),
                new, old)

        g_params, g_rest = _split_state(global_state)
        stack = lambda t: jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (L,) + a.shape), t)
        sg_params, sg_rest = stack(g_params), stack(g_rest)
        # per-lane init (NOT init-of-stacked): leaves like Adam's count
        # must carry a lane axis so divergent lanes can be selected
        sg_opt = jax.vmap(optimizer.init)(sg_params)

        def batch_at(i):
            idx_b = jax.lax.dynamic_index_in_dim(
                lanes["idx"], i, axis=1, keepdims=False)  # [L, B]
            mask_b = jax.lax.dynamic_index_in_dim(
                lanes["mask"], i, axis=1, keepdims=False)
            slot = jax.lax.dynamic_index_in_dim(
                lanes["slot"], i, axis=1, keepdims=False)  # [L]
            row = jnp.take(rows, slot)
            flat = row[:, None] * n_max + idx_b  # [L, B]
            x = jnp.take(data_x, flat.reshape(-1), axis=0).reshape(
                flat.shape + data_x.shape[1:])
            y = jnp.take(data_y, flat.reshape(-1), axis=0).reshape(
                flat.shape + data_y.shape[1:])
            return {"x": x, "y": y, "mask": mask_b}

        def grad_at(params, rest, batch, step_rngs):
            if spec.augment_fn is not None:
                batch = dict(batch)
                batch["x"] = jax.vmap(
                    lambda xx, k: spec.augment_fn(
                        xx, jax.random.fold_in(k, 13)))(
                    batch["x"], step_rngs)

            def loss_wrapper(p):
                state = dict(rest)
                state["params"] = p
                return lane_loss_fn(state, batch, step_rngs, True)

            return jax.value_and_grad(loss_wrapper, has_aux=True)(params)

        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda: grad_at(
                sg_params, sg_rest, batch_at(0),
                step_keys[:, 0]))[0][1][1])
        aux0 = {"n": jnp.zeros((L,), jnp.float32),
                "steps": jnp.zeros((L,), jnp.int32)}
        vpayload = jax.vmap(payload_fn, in_axes=(0, None, 0))
        pay0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, jnp.float32),
            jax.eval_shape(vpayload, {**sg_rest, "params": sg_params},
                           global_state, aux0))

        def body(i, carry):
            params, rest, opt_state, pay, w, msum = carry
            batch = batch_at(i)
            step_rngs = jax.lax.dynamic_index_in_dim(
                step_keys, i, axis=1, keepdims=False)  # [L, 2]
            (_, (new_state, metrics)), grads = grad_at(
                params, rest, batch, step_rngs)
            updates, new_opt = jax.vmap(optimizer.update)(
                grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_rest = {k: new_state[k] for k in rest}
            valid = jnp.sum(batch["mask"], axis=1) > 0  # [L]
            params, rest, opt_state = _select(
                valid, (new_params, new_rest, new_opt),
                (params, rest, opt_state))
            msum = jax.tree.map(jnp.add, msum, metrics)

            f = jax.lax.dynamic_index_in_dim(
                lanes["flush"], i, axis=1, keepdims=False)  # [L]
            f_n = jax.lax.dynamic_index_in_dim(
                lanes["flush_n"], i, axis=1, keepdims=False)
            f_steps = jax.lax.dynamic_index_in_dim(
                lanes["flush_steps"], i, axis=1, keepdims=False)
            local_state = dict(rest)
            local_state["params"] = params
            payload = vpayload(local_state, global_state,
                               {"n": f_n, "steps": f_steps.astype(jnp.int32)})
            scale = f * f_n  # [L]
            pay = jax.tree.map(
                lambda a, p: a + scale.reshape(
                    (L,) + (1,) * (p.ndim - 1)) * p.astype(jnp.float32),
                pay, payload)
            w = w + scale
            params, rest, opt_state = _select(
                f > 0, (sg_params, sg_rest, sg_opt),
                (params, rest, opt_state))
            return (params, rest, opt_state, pay, w, msum)

        carry = (sg_params, sg_rest, sg_opt, pay0, jnp.zeros((L,),
                                                             jnp.float32),
                 metrics0)
        _, _, _, pay, w, msum = jax.lax.fori_loop(0, trip, body, carry)
        return pay, w, msum

    return packed_update


class LaneRunner:
    """Packed-lane execution: the WHOLE round as ONE jitted dispatch.

    ``pack_lanes`` lays the cohort's per-client step schedules end-to-end
    into K balanced lanes (LPT). Each lane's ``fori_loop`` trains clients
    back-to-back: at a client's final step the lane flushes the weighted
    payload into an on-device accumulator and resets its carried state to
    the global model, so no lane ever executes a padded fwd+bwd. Wall
    steps per round = max lane load ~= ceil(total_steps / K): strictly
    less straggle than size-sorted waves (``WaveRunner``), with a single
    program launch per round. RNG per client step is
    ``fold_in(client_key, local_step)`` with the same client keys as the
    flat paths, so lane, wave, and flat trajectories agree to float
    reassociation (tested in ``tests/test_engine.py``).

    Reference contrast: one torch process per client, rounds gated on the
    slowest process (``FedAVGAggregator.py:58-87``); here the scheduler
    is ~50 lines of host numpy and the chip never idles.
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig,
                 payload_fn=None, server_fn=None, n_lanes=8, packed=False):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.n_lanes = int(n_lanes or 8)
        self.packed = bool(packed)
        if self.packed:
            # MXU-shaped lowering: lane axis folded into channels by the
            # spec's lane_loss_builder (raises if the model family has
            # none) instead of vmap over lane-stacked weights
            packed_update = make_packed_lane_update(
                spec, cfg, self.payload_fn, self.n_lanes)
        else:
            lane_update = make_lane_update(spec, cfg, self.payload_fn)
        server_fn_ = self.server_fn

        @partial(jax.jit, donate_argnums=(0, 1))
        def round_fn(global_state, server_state, device_x, device_y, rows,
                     lanes, step_keys, trip, dtypes, rng):
            R, n_max = device_x.shape[0], device_x.shape[1]
            dx = device_x.reshape((R * n_max,) + device_x.shape[2:])
            dy = device_y.reshape((R * n_max,) + device_y.shape[2:])
            if self.packed:
                pay, w, msum = packed_update(
                    global_state, dx, dy, n_max, rows, lanes, step_keys,
                    trip)
            else:
                pay, w, msum = jax.vmap(
                    lane_update, in_axes=(None, None, None, None, None, 0,
                                          0, None))(
                    global_state, dx, dy, n_max, rows, lanes, step_keys,
                    trip)
            pay_sum = jax.tree.map(lambda x: jnp.sum(x, axis=0), pay)
            w_sum = jnp.sum(w)
            metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), msum)
            avg = jax.tree.map(
                lambda s, d: (s / jnp.maximum(w_sum, 1e-12)).astype(d.dtype),
                pay_sum, dtypes)
            new_global, new_server = server_fn_(global_state, avg,
                                                server_state, rng)
            return new_global, new_server, metrics_sum

        self._round_fn = round_fn
        self._fold_keys = fold_step_keys
        self._dtypes = None

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def run_round(self, global_state, server_state, device_data, ids, sched,
                  rng):
        """Same contract as :meth:`WaveRunner.run_round` (cohort ``ids``
        into ``device_data``, full ``pack_schedule`` output, round key);
        executes as one dispatch over ``n_lanes`` packed lanes."""
        import numpy as np

        from fedml_tpu.parallel.packing import pack_lanes

        C = len(np.asarray(sched["n"]))
        lanes = pack_lanes(sched, self.n_lanes)
        trip = jnp.int32(max(lanes.pop("trip"), 1))
        client_keys = jax.random.split(jax.random.fold_in(rng, 1), C)
        lane_arrays = {k: jnp.asarray(v) for k, v in lanes.items()
                       if k in ("idx", "mask", "slot", "flush", "flush_n",
                                "flush_steps")}
        step_keys = self._fold_keys(client_keys,
                                    jnp.asarray(lanes["slot"]),
                                    jnp.asarray(lanes["local_step"]))
        rows = jnp.asarray(np.asarray(ids, np.int32))
        with get_tracer().span("lanes", clients=int(C),
                               n_lanes=int(self.n_lanes), trip=int(trip)):
            new_global, new_server, metrics = self._round_fn(
                global_state, server_state, device_data["x"],
                device_data["y"], rows, lane_arrays, step_keys, trip,
                self._payload_dtypes(global_state),
                jax.random.fold_in(rng, 2))
        steps_pc = (np.asarray(sched["mask"]).sum(axis=2) > 0).sum(axis=1)
        aux = {"n": np.asarray(sched["n"], np.float32),
               "steps": steps_pc.astype(np.int64)}
        return new_global, new_server, {"aux": aux, "metrics": metrics}


class ShardedLaneRunner:
    """Packed lanes over a ``clients`` mesh: the multi-chip round as one
    SPMD dispatch with zero padded compute per shard.

    Client shards live in HBM sharded over the mesh's ``clients`` axis
    (each device owns a contiguous block of client rows); every mesh shard
    runs ITS resident cohort members as LPT-packed lanes (the
    :func:`make_lane_update` program), then the weighted payload sums meet
    in a ``psum`` over ICI and the server step runs replicated. This
    composes the single-chip lane design with the reference's multi-worker
    scaling story (SURVEY.md section 2.7/2.8): where the reference gates
    every round on its slowest client process and moves pickled
    state_dicts through MPI, here the only cross-chip traffic is one
    weighted-payload reduction.

    The fori_loop trip count is the max lane load across ALL shards
    (uniform SPMD control flow); shards with lighter loads run guarded
    no-op steps for the difference, so balance comes from placing clients
    on shards evenly (``FedAvgAPI`` places contiguous blocks; LDA skew
    within a block is absorbed by the in-shard LPT packing).
    """

    def __init__(self, spec: TrainSpec, cfg: ClientUpdateConfig, mesh,
                 payload_fn=None, server_fn=None, n_lanes=8, packed=False):
        self.payload_fn = payload_fn or _default_payload
        self.server_fn = server_fn or _default_server
        self.n_lanes = int(n_lanes or 8)
        self.mesh = mesh
        self.packed = bool(packed)
        if self.packed:
            # each shard runs ITS lanes through the MXU-shaped lowering
            # (models/lane_packed.py); the cross-chip psum is unchanged
            packed_update = make_packed_lane_update(
                spec, cfg, self.payload_fn, self.n_lanes)
        else:
            lane_update = make_lane_update(spec, cfg, self.payload_fn)
        server_fn_ = self.server_fn

        def shard_fn(global_state, server_state, dx, dy, rows, lanes,
                     step_keys, trip, dtypes, rng):
            # leading mesh axis arrives size-1 under shard_map: squeeze
            rows_l = rows[0]
            lanes_l = jax.tree.map(lambda a: a[0], lanes)
            keys_l = step_keys[0]
            R_local, n_max = dx.shape[0], dx.shape[1]
            dxf = dx.reshape((R_local * n_max,) + dx.shape[2:])
            dyf = dy.reshape((R_local * n_max,) + dy.shape[2:])
            if self.packed:
                pay, w, msum = packed_update(
                    global_state, dxf, dyf, n_max, rows_l, lanes_l,
                    keys_l, trip)
            else:
                pay, w, msum = jax.vmap(
                    lane_update,
                    in_axes=(None, None, None, None, None, 0, 0, None))(
                    global_state, dxf, dyf, n_max, rows_l, lanes_l, keys_l,
                    trip)
            pay_sum = jax.tree.map(
                lambda x: jax.lax.psum(jnp.sum(x, axis=0), CLIENT_AXIS),
                pay)
            w_sum = jax.lax.psum(jnp.sum(w), CLIENT_AXIS)
            metrics = jax.tree.map(
                lambda m: jax.lax.psum(jnp.sum(m, axis=0), CLIENT_AXIS),
                msum)
            avg = jax.tree.map(
                lambda s, d: (s / jnp.maximum(w_sum, 1e-12)).astype(d.dtype),
                pay_sum, dtypes)
            new_global, new_server = server_fn_(global_state, avg,
                                                server_state, rng)
            return new_global, new_server, metrics

        sharded = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(), P(), P(CLIENT_AXIS), P(CLIENT_AXIS),
                      P(CLIENT_AXIS), P(CLIENT_AXIS), P(CLIENT_AXIS),
                      P(), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False)
        self._round_fn = jax.jit(sharded, donate_argnums=(0, 1))
        self._fold_keys = fold_step_keys
        self._dtypes = None

    def _payload_dtypes(self, global_state):
        if self._dtypes is None:
            self._dtypes = payload_dtype_template(self.payload_fn,
                                                  global_state)
        return self._dtypes

    def run_round(self, global_state, server_state, device_data, ids, sched,
                  rng):
        """Same contract as :meth:`LaneRunner.run_round`; ``device_data``
        is SHARDED over the mesh's client axis (row blocks of size
        ``R / D``), and ``ids`` are global device rows."""
        import numpy as np

        from fedml_tpu.parallel.packing import pack_lanes

        mask = np.asarray(sched["mask"])
        C = mask.shape[0]
        D = self.mesh.shape[CLIENT_AXIS]
        R = int(device_data["x"].shape[0])
        assert R % D == 0, (R, D)
        block = R // D
        ids = np.asarray(ids, np.int64)
        K = self.n_lanes

        # split the cohort by owning shard; size lanes with the cheap
        # max-load query, pack arrays once per shard below
        from fedml_tpu.parallel.packing import lane_max_load

        steps_pc_all = (mask.sum(axis=2) > 0).sum(axis=1)
        per_shard = []
        l_needed = 1
        for d in range(D):
            members = np.nonzero((ids >= d * block)
                                 & (ids < (d + 1) * block))[0]
            sub = {k: np.asarray(sched[k])[members]
                   for k in ("idx", "mask", "n")}
            if len(members) == 0:
                sub = {"idx": np.zeros((1,) + mask.shape[1:], np.int32),
                       "mask": np.zeros((1,) + mask.shape[1:], np.float32),
                       "n": np.zeros((1,), np.float32)}
            else:
                l_needed = max(l_needed,
                               lane_max_load(steps_pc_all[members], K))
            per_shard.append((members, sub))

        # uniform allocation across shards (SPMD arrays must stack);
        # power-of-two bucket bounds recompiles across rounds
        L = 8
        while L < l_needed:
            L *= 2

        client_keys = jax.random.split(jax.random.fold_in(rng, 1), C)
        keys_np = np.asarray(client_keys)
        lane_stack, key_stack, row_stack, trips = [], [], [], []
        for d, (members, sub) in enumerate(per_shard):
            lanes = pack_lanes(sub, K, l_max=L)
            trips.append(lanes.pop("trip"))
            local_step = lanes.pop("local_step")
            k_sub = lanes["idx"].shape[0]
            if k_sub < K:  # pack_lanes clamps K to the member count;
                # pad with inert zero lanes so shards stack uniformly
                lanes = {k: np.concatenate(
                    [v, np.zeros((K - k_sub,) + v.shape[1:], v.dtype)])
                    for k, v in lanes.items()}
                local_step = np.concatenate(
                    [local_step,
                     np.zeros((K - k_sub,) + local_step.shape[1:],
                              local_step.dtype)])
            # slot -> LOCAL device row for this shard's member list
            rows_local = np.zeros((max(block, 1),), np.int32)
            if len(members):
                rows_local[:len(members)] = ids[members] - d * block
                member_keys = keys_np[members]
            else:
                member_keys = keys_np[:1]
            lane_stack.append(lanes)
            key_stack.append(self._fold_keys(
                jnp.asarray(member_keys), jnp.asarray(lanes["slot"]),
                jnp.asarray(local_step)))
            row_stack.append(rows_local)
        lanes_all = jax.tree.map(
            lambda *xs: jnp.asarray(np.stack(xs)), *lane_stack)
        keys_all = jnp.stack(key_stack)
        rows_all = jnp.asarray(np.stack(row_stack))
        trip = jnp.int32(max(max(trips), 1))

        with get_tracer().span("sharded-lanes", clients=int(C),
                               shards=int(D), trip=int(max(max(trips), 1))):
            new_global, new_server, metrics = self._round_fn(
                global_state, server_state, device_data["x"],
                device_data["y"], rows_all, lanes_all, keys_all, trip,
                self._payload_dtypes(global_state),
                jax.random.fold_in(rng, 2))
        steps_pc = (mask.sum(axis=2) > 0).sum(axis=1)
        aux = {"n": np.asarray(sched["n"], np.float32),
               "steps": steps_pc.astype(np.int64)}
        return new_global, new_server, {"aux": aux, "metrics": metrics}


def make_indexed_sim_round(spec: TrainSpec, cfg: ClientUpdateConfig,
                           payload_fn=None, server_fn=None,
                           client_chunk=None):
    """Single-chip round over device-resident data + index schedules.

    ``fn(global_state, server_state, device_data, sched, rng)`` with
    ``device_data`` leading axis = cohort clients. ``client_chunk`` bounds
    peak activation memory: clients run in sequential waves of ``chunk``
    (``lax.map`` outer, ``vmap`` inner) instead of all at once -- the knob
    that lets 32-client ResNet cohorts fit one chip's HBM.
    """
    client_update = make_indexed_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    @partial(jax.jit, donate_argnums=(0, 1))
    def round_fn(global_state, server_state, device_data, sched, rng):
        C = sched["mask"].shape[0]
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        server_rng = jax.random.fold_in(rng, 2)

        def run(d, s, r):
            return jax.vmap(client_update, in_axes=(None, 0, 0, 0))(
                global_state, d, s, r)

        chunk = client_chunk
        if chunk is not None and chunk < C:
            # pad the cohort to a chunk multiple with fully-masked dummy
            # clients (the shared zero_pad_leading invariant) so the
            # memory knob works for any cohort size
            pad = (-C) % chunk
            if pad:
                device_data = zero_pad_leading(device_data, pad, jnp)
                sched_p = zero_pad_leading(sched, pad, jnp)
                rngs_p = jnp.concatenate([rngs, rngs[:1].repeat(pad, 0)])
            else:
                sched_p, rngs_p = sched, rngs
            Cp = C + pad
            waves = Cp // chunk
            reshard = lambda a: a.reshape((waves, chunk) + a.shape[1:])
            dd = jax.tree.map(reshard, device_data)
            ss = jax.tree.map(reshard, sched_p)
            rr = reshard(rngs_p)
            local_states, aux, metrics = jax.lax.map(
                lambda args: run(*args), (dd, ss, rr))
            unshard = lambda a: a.reshape((Cp,) + a.shape[2:])[:C]
            local_states, aux, metrics = jax.tree.map(
                unshard, (local_states, aux, metrics))
        else:
            local_states, aux, metrics = run(device_data, sched, rngs)

        payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
            local_states, global_state, aux)
        avg_payload = pytree.tree_weighted_mean(payloads, aux["n"])
        new_global, new_server_state = server_fn(
            global_state, avg_payload, server_state, server_rng)
        return new_global, new_server_state, {"aux": aux, "metrics": metrics}

    return round_fn


def _default_payload(local_state, global_state, aux):
    return local_state


def _default_server(global_state, avg_payload, server_state, rng):
    return avg_payload, server_state


def payload_dtype_template(payload_fn, global_state):
    """Zero-scalar pytree carrying the payload's dtypes (the accumulators
    run in f32; the final average casts back through this template).
    Shared by every accumulate-then-normalize runner."""
    aux = {"n": jax.ShapeDtypeStruct((), jnp.float32),
           "steps": jax.ShapeDtypeStruct((), jnp.int32)}
    shapes = jax.eval_shape(payload_fn, global_state, global_state, aux)
    return jax.tree.map(lambda s: jnp.zeros((), s.dtype), shapes)


@jax.jit
def fold_step_keys(client_keys, slot, local_step):
    """Per-step PRNG keys for packed lanes:
    ``keys[k, i] = fold_in(client_keys[slot[k, i]], local_step[k, i])`` --
    the exact per-client-step derivation of the flat paths."""

    def one(s, t):
        return jax.random.fold_in(jnp.take(client_keys, s, axis=0), t)

    return jax.vmap(jax.vmap(one))(slot, local_step)


def make_sim_round(spec: TrainSpec, cfg: ClientUpdateConfig,
                   payload_fn=None, server_fn=None):
    """Single-chip round: clients vmapped over the cohort axis.

    ``fn(global_state, server_state, cohort_data, rng) ->
    (new_global, new_server_state, metrics)`` -- semantics of the reference
    standalone loop (``fedavg_api.py:40-115``) in one jitted call.
    """
    client_update = make_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    @partial(jax.jit, donate_argnums=(0, 1))
    def round_fn(global_state, server_state, cohort_data, rng):
        C = cohort_data["mask"].shape[0]
        # identical rng derivation as make_sharded_round so the two placements
        # produce bit-identical trajectories for stochastic models too
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        server_rng = jax.random.fold_in(rng, 2)
        local_states, aux, metrics = jax.vmap(
            client_update, in_axes=(None, 0, 0))(global_state, cohort_data, rngs)
        payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
            local_states, global_state, aux)
        avg_payload = pytree.tree_weighted_mean(payloads, aux["n"])
        new_global, new_server_state = server_fn(
            global_state, avg_payload, server_state, server_rng)
        return new_global, new_server_state, {"aux": aux, "metrics": metrics}

    return round_fn


def make_sharded_round(spec: TrainSpec, cfg: ClientUpdateConfig, mesh,
                       payload_fn=None, server_fn=None):
    """Pod-scale round: cohort sharded over the ``clients`` mesh axis.

    Each shard trains ``C / n_shards`` clients (vmapped locally), then the
    weighted average runs as ``psum`` collectives over ICI -- the TPU-native
    replacement for MPISendThread + CPU aggregation (reference
    ``mpi/com_manager.py:36-79`` + ``FedAVGAggregator.py:58-87``).
    Works on any mesh size including 1x1, so the same code path serves
    single-chip runs and pod slices.
    """
    client_update = make_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    def shard_fn(global_state, server_state, cohort_data, rng):
        # leading axis of cohort_data here is the *local* client count C/D
        local_states, aux, metrics = jax.vmap(
            client_update, in_axes=(None, 0, 0))(
                global_state, cohort_data, cohort_data["rngs"])
        payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
            local_states, global_state, aux)
        w = aux["n"].astype(jnp.float32)
        local_sum = jax.tree.map(
            lambda x: jnp.tensordot(w, x.astype(jnp.float32), axes=(0, 0)), payloads)
        total = jnp.maximum(jax.lax.psum(jnp.sum(w), CLIENT_AXIS), 1e-12)
        avg_payload = jax.tree.map(
            lambda x, t: (jax.lax.psum(x, CLIENT_AXIS) / total).astype(t.dtype),
            local_sum, jax.tree.map(lambda x: x[0], payloads))
        new_global, new_server_state = server_fn(
            global_state, avg_payload, server_state, rng)
        return new_global, new_server_state, {"aux": aux, "metrics": metrics}

    sharded = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(CLIENT_AXIS), P()),
        out_specs=(P(), P(), P(CLIENT_AXIS)),
        check_vma=False)

    @partial(jax.jit, donate_argnums=(0, 1))
    def round_fn(global_state, server_state, cohort_data, rng):
        C = cohort_data["mask"].shape[0]
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        data = dict(cohort_data)
        data["rngs"] = rngs
        return sharded(global_state, server_state, data,
                       jax.random.fold_in(rng, 2))

    return round_fn


def make_eval_fn(spec: TrainSpec):
    """Jitted evaluation over packed masked batches (``pack_eval`` output).
    Returns summed metric dict; divide by counts on host. Mirrors the
    reference eval protocol (``FedAVGAggregator.py:99-163``) with the model
    kept on device."""

    @jax.jit
    def eval_fn(state, data):
        def step(carry, batch):
            m = spec.metrics_fn(state, batch)
            return carry, m

        _, ms = jax.lax.scan(step, 0, {k: data[k] for k in ("x", "y", "mask")})
        return jax.tree.map(lambda x: jnp.sum(x, axis=0), ms)

    return eval_fn
