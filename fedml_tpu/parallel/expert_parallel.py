"""Expert parallelism (ep): MoE experts sharded over an ``expert`` axis.

The last letter of the mesh-parallelism inventory (dp
:mod:`fedml_tpu.parallel.engine`, sp :mod:`.seq_parallel`, tp
:mod:`.tensor_parallel`, pp :mod:`.pipeline_parallel`): the stacked
expert weights of :class:`fedml_tpu.models.moe.MoEMLP` (``wi [E, C, H]``,
``wo [E, H, C]``) get ``P(expert)`` on their leading axis and GSPMD
partitions the dispatch/expert/combine einsums -- each device computes
its experts' token buffers, the combine einsum's contraction over ``E``
becomes the all-reduce. No manual collectives, same step contract as the
sp/tp builders.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.mesh import make_2d_mesh

DATA_AXIS = "data"
EXPERT_AXIS = "expert"

# coefficient on the Switch load-balancing aux loss -- single-sourced so
# the step builder and its oracles (tests, dryrun) cannot drift
MOE_AUX_WEIGHT = 0.01


def make_ep_mesh(n_data: int, n_expert: int, devices=None):
    return make_2d_mesh(n_data, n_expert, (DATA_AXIS, EXPERT_AXIS),
                        devices)


def ep_param_shardings(params, mesh, n_experts=None):
    """Expert weights shard over ``expert``; everything else replicates.

    A leaf is an expert stack only when it is named ``wi``/``wo`` AND
    lives under an ``moe`` module (anchored on path components -- a future
    non-expert param merely *ending* in "wi" must not silently shard,
    ADVICE r3). The leading axis must divide the expert mesh axis (and
    equal ``n_experts`` when given), else this raises.
    """
    n_ep = mesh.shape[EXPERT_AXIS]

    def lookup(path, leaf):
        parts = [str(p.key) for p in path if hasattr(p, "key")]
        expert = "moe" in parts[:-1] and parts[-1] in ("wi", "wo")
        if not expert:
            return NamedSharding(mesh, P())
        if n_experts is not None and leaf.shape[0] != n_experts:
            raise ValueError(
                f"ep_param_shardings: '{'/'.join(parts)}' leading axis "
                f"{leaf.shape[0]} != n_experts={n_experts}")
        if leaf.shape[0] % n_ep:
            raise ValueError(
                f"ep_param_shardings: '{'/'.join(parts)}' has "
                f"{leaf.shape[0]} experts, not divisible by the "
                f"{n_ep}-way expert mesh axis")
        return NamedSharding(mesh, P(EXPERT_AXIS))

    return jax.tree_util.tree_map_with_path(lookup, params)


def make_ep_lm_step(model, mesh, tx: Optional[Any] = None,
                    data_axis: str = DATA_AXIS):
    """``(init_fn, step_fn)`` for an MoE LM (``model.apply`` returning
    logits, with MoE aux losses sown into the ``losses`` collection)."""
    from fedml_tpu.models.transformer import lm_loss

    tx = tx if tx is not None else optax.sgd(1e-3)
    x_sh = NamedSharding(mesh, P(data_axis, None))

    def init_fn(rng, example_idx):
        vs = model.init(rng, example_idx)
        p_sh = ep_param_shardings(vs["params"], mesh,
                                  getattr(model, "n_experts", None))
        params = jax.tree.map(jax.device_put, vs["params"], p_sh)
        return params, tx.init(params)

    def loss_fn(params, idx, tgt):
        logits, aux = model.apply({"params": params}, idx,
                                  mutable=["losses"])
        moe_aux = sum(jax.tree.leaves(aux.get("losses", {})), 0.0)
        return lm_loss(logits, tgt) + MOE_AUX_WEIGHT * moe_aux

    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, idx, tgt):
        idx = jax.lax.with_sharding_constraint(idx, x_sh)
        tgt = jax.lax.with_sharding_constraint(tgt, x_sh)
        loss, grads = jax.value_and_grad(loss_fn)(params, idx, tgt)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return init_fn, step_fn


__all__ = ["make_ep_mesh", "make_ep_lm_step", "ep_param_shardings",
           "MOE_AUX_WEIGHT",
           "DATA_AXIS", "EXPERT_AXIS"]
