"""Pipeline parallelism (pp): GPipe-style stage-sharded transformer.

Completes the mesh-parallelism inventory next to client-DP
(:mod:`fedml_tpu.parallel.engine`), sp (:mod:`.seq_parallel`) and tp
(:mod:`.tensor_parallel`): transformer blocks shard over a ``stage`` mesh
axis -- ``k = n_layers / n_stages`` consecutive blocks per stage, applied
as one weight-scanned ``lax.scan`` -- and microbatches flow through the
ring: each tick every stage applies its blocks to the activation it holds
and ``ppermute``s the result one hop downstream; after ``M + S - 1`` ticks
all ``M`` microbatches have drained. Backward is ``jax.grad`` straight
through the scanned body: JAX transposes ``ppermute`` to the reverse
rotation (which IS the backward pipeline schedule) and psum-reduces
cotangents of the shared embed/head params, so every device steps
identically.

Embed and head/loss execute ONLY on their owning stages (first and last)
via ``lax.cond`` on ``axis_index`` -- per-device control flow is legal
inside ``shard_map`` as long as no collective hides in a branch; the other
stages skip those FLOPs entirely. Their parameters stay replicated (O(V d)
memory, the price of a uniform optimizer step), but the redundant compute
of the one-block-per-stage prototype is gone.

The reference has no pipeline concept -- its biggest model is served by
replicating it per GPU (``GKTServerTrainer.py:28-29``). This is the
TPU-native answer for models deeper than one chip's HBM.

Restrictions (by design, to stay one compiled program): ``n_layers`` must
be a multiple of ``n_stages`` and the global batch must split into
``n_micro`` equal microbatches.
"""

from __future__ import annotations

import re
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.sharding import shard_map
from fedml_tpu.models.transformer import TransformerLM, _Block, lm_loss

STAGE_AXIS = "stage"


def make_pp_mesh(n_stages: int, devices=None):
    devices = list(jax.devices()) if devices is None else list(devices)
    if n_stages > len(devices):
        raise ValueError(f"mesh needs {n_stages} devices, "
                         f"have {len(devices)}")
    return Mesh(np.array(devices[:n_stages]), (STAGE_AXIS,))


def _count_blocks(params) -> int:
    pat = re.compile(r"^block(\d+)$")
    idxs = sorted(int(m.group(1)) for k in params
                  if (m := pat.match(k)) is not None)
    if idxs != list(range(len(idxs))):
        raise ValueError(f"non-contiguous block keys in params: {idxs}")
    return len(idxs)


def init_pp_params(mesh, rng, example_idx, *, vocab_size, n_heads=4,
                   d_model=256, max_len=2048, mlp_ratio=4,
                   dtype=jnp.float32, attention_fn=None, n_layers=None):
    """Init a ``TransformerLM`` with ``n_layers`` blocks (default: one per
    pipeline stage) and re-layout: per-block params stacked to
    ``[S, k, ...]`` (stage-major, sharded over ``stage``), embeddings /
    final-LN / head replicated.

    Returns ``(params, model)`` where ``model`` carries the architecture
    config the step builder needs. ``model.apply`` on the UN-stacked
    params is the single-device oracle.
    """
    S = mesh.shape[STAGE_AXIS]
    n_layers = S if n_layers is None else int(n_layers)
    if n_layers % S:
        raise ValueError(f"n_layers={n_layers} must be a multiple of the "
                         f"{S}-stage mesh")
    model = TransformerLM(vocab_size=vocab_size, n_layers=n_layers,
                          n_heads=n_heads, d_model=d_model, max_len=max_len,
                          mlp_ratio=mlp_ratio, dtype=dtype,
                          attention_fn=attention_fn)
    vs = model.init(rng, example_idx)
    host = stack_pp_params(vs["params"], S)
    st_sh = jax.tree.map(
        lambda _: NamedSharding(mesh, P(STAGE_AXIS)), host["stages"])
    rep_sh = jax.tree.map(lambda _: NamedSharding(mesh, P()),
                          host["shared"])
    params = {"stages": jax.tree.map(jax.device_put, host["stages"], st_sh),
              "shared": jax.tree.map(jax.device_put, host["shared"],
                                     rep_sh)}
    return params, model


def stack_pp_params(params, n_stages):
    """Single-device TransformerLM params -> the pp layout (host-side, no
    mesh placement): block ``s*k + j`` becomes ``stages[s, j]`` -- stage
    ``s`` owns ``k`` consecutive blocks. For oracle comparisons in tests.
    """
    p = dict(params)
    n_blocks = _count_blocks(p)
    if n_blocks == 0 or n_blocks % n_stages:
        raise ValueError(
            f"model has {n_blocks} blocks -- pp requires a nonzero "
            f"multiple of n_stages={n_stages} (a remainder would silently "
            "ride in 'shared' untrained)")
    k = n_blocks // n_stages
    blocks = [p.pop(f"block{i}") for i in range(n_blocks)]
    stages = [jax.tree.map(lambda *xs: jnp.stack(xs),
                           *blocks[s * k:(s + 1) * k])
              for s in range(n_stages)]
    return {"stages": jax.tree.map(lambda *xs: jnp.stack(xs), *stages),
            "shared": p}


def unstack_pp_params(pp_params, n_stages):
    """Inverse of :func:`stack_pp_params` (e.g. to checkpoint in the
    standard TransformerLM layout)."""
    out = dict(pp_params["shared"])
    k = jax.tree.leaves(pp_params["stages"])[0].shape[1]
    for s in range(n_stages):
        for j in range(k):
            out[f"block{s * k + j}"] = jax.tree.map(
                lambda a, s=s, j=j: a[s, j], pp_params["stages"])
    return out


def make_pp_lm_step(model: TransformerLM, mesh, tx: Optional[Any] = None,
                    n_micro: int = 4):
    """Build ``(prep_fn, step_fn)`` for pp training.

    ``prep_fn(idx, tgt)`` splits ``[B, T]`` into ``[M, B/M, T]``
    microbatches; ``step_fn(params, opt_state, idx_m, tgt_m) -> (params,
    opt_state, loss)`` with params from :func:`init_pp_params`.
    """
    tx = tx if tx is not None else optax.sgd(1e-3)
    S = mesh.shape[STAGE_AXIS]
    if model.n_layers % S:
        raise ValueError(
            f"pp requires whole blocks per stage: model.n_layers="
            f"{model.n_layers} is not a multiple of the {S}-stage mesh")
    block = _Block(model.n_heads, model.mlp_ratio, model.dtype,
                   model.attention_fn)
    tok = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype)
    pos = nn.Embed(model.max_len, model.d_model, dtype=model.dtype)
    ln_f = nn.LayerNorm(dtype=model.dtype)
    head = nn.Dense(model.vocab_size, dtype=jnp.float32)

    def _body(stage_params, shared, idx, tgt):
        me = jax.lax.axis_index(STAGE_AXIS)
        my_blocks = jax.tree.map(lambda a: a[0], stage_params)  # [k, ...]
        M, mB, T = idx.shape

        def embed(t_idx):
            x = tok.apply({"params": shared["tok_embed"]}, t_idx)
            x = x + pos.apply({"params": shared["pos_embed"]},
                              jnp.arange(T)[None])
            return x.astype(jnp.float32)

        def apply_my_blocks(x):
            # k consecutive blocks, weight-scanned over the leading axis
            def one(h, bp):
                return block.apply({"params": bp}, h), None
            h, _ = jax.lax.scan(one, x.astype(model.dtype), my_blocks)
            return h.astype(jnp.float32)

        zeros = jnp.zeros((mB, T, model.d_model), jnp.float32)
        outs0 = jnp.zeros((M, mB, T, model.d_model), jnp.float32)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 injects microbatch t while the queue lasts; other
            # stages skip the embed FLOPs entirely (owning-stage compute)
            x = jax.lax.cond(
                me == 0,
                lambda: jnp.where(t < M,
                                  embed(idx[jnp.minimum(t, M - 1)]), zeros),
                lambda: buf)
            h = apply_my_blocks(x)
            # last stage banks microbatch t - (S - 1) as it completes
            oi = t - (S - 1)
            outs = jnp.where(
                (me == S - 1) & (oi >= 0),
                jax.lax.dynamic_update_index_in_dim(
                    outs, h, jnp.maximum(oi, 0), axis=0),
                outs)
            buf = jax.lax.ppermute(
                h, STAGE_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(tick, (zeros, outs0),
                                    jnp.arange(M + S - 1))

        # head + loss ONLY on the owning (last) stage; psum replicates the
        # value (and its transpose psum-reduces the shared-param
        # cotangents, so embed/head grads come out replicated too)
        def head_loss(o):
            x = ln_f.apply({"params": shared["ln_f"]},
                           o.reshape(M * mB, T, -1).astype(model.dtype))
            logits = head.apply({"params": shared["head"]},
                                x.astype(jnp.float32))
            return lm_loss(logits, tgt.reshape(M * mB, T))

        local = jax.lax.cond(me == S - 1, head_loss,
                             lambda o: jnp.float32(0.0), outs)
        return jax.lax.psum(local, STAGE_AXIS)

    def prep_fn(idx, tgt):
        B = idx.shape[0]
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by "
                             f"n_micro={n_micro}")
        shp = (n_micro, B // n_micro) + idx.shape[1:]
        return idx.reshape(shp), tgt.reshape(shp)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, idx_m, tgt_m):
        def lf(p):
            sm = shard_map(
                _body, mesh=mesh,
                in_specs=(jax.tree.map(lambda _: P(STAGE_AXIS),
                                       p["stages"]),
                          jax.tree.map(lambda _: P(), p["shared"]),
                          P(), P()),
                out_specs=P(), check_vma=False)
            return sm(p["stages"], p["shared"], idx_m, tgt_m)

        loss, grads = jax.value_and_grad(lf)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, loss

    return prep_fn, step_fn


__all__ = ["make_pp_mesh", "init_pp_params", "make_pp_lm_step",
           "stack_pp_params", "unstack_pp_params", "STAGE_AXIS"]
