"""Closed-loop pace steering: the aggregator tunes its own knobs.

Bonawitz et al. (MLSys 2019, section 3) describe *pace steering* as the
control loop that keeps a federated population productive: the server
watches its own arrival distributions and moves the round knobs --
report deadline, over-selection, and (since FedBuff) the async buffer
size and flush deadline -- instead of an operator guessing them once.
PR 10 built exactly the inputs that loop needs (the
``fed_report_latency_seconds`` straggler tails, staleness/buffer-depth
histograms, and the rolling ``fed_rounds_per_hour`` gauge in the
metrics registry); this module is the consumer.

:class:`PaceController` is a *deterministic* controller: every decision
is a pure function of its configuration, its previous decision, and the
observations handed to it -- there is no hidden randomness and no
wall-clock read inside the law, so a replayed trace with the same seed
reproduces the identical decision sequence (the determinism test pins
this, and the simulation path is bitwise-reproducible end to end). The
``seed`` is carried for the optional exploration dither, which defaults
to 0 (off).

Control law (documented operator-facing in docs/RESILIENCE.md):

- **report deadline** (sync rounds): track the straggler tail. With a
  windowed report-latency p90 available, the target is
  ``latency_margin * p90``; the deadline moves toward it by at most
  ``step_up``x upward or ``step_down``x downward per decision and is
  clamped to ``bounds.deadline_s``. An *abandoned* round overrides the
  tracker: the deadline multiplies by ``abandon_backoff`` immediately
  (the tail escaped the histogram window -- back off first, re-track
  once reports flow again).
- **over-selection**: track the observed loss fraction
  ``1 - reporting/selected``. The target ``eps`` is the loss odds
  ``loss / (1 - loss)`` times ``overselect_safety``; eps moves by at
  most ``overselect_max_delta`` per decision within
  ``bounds.overselect``.
- **async buffer K**: size the buffer to what actually arrives within
  one flush deadline: ``arrival_rate * flush_deadline * fill_fraction``,
  geometric-rate-limited by ``step_up``/``step_down`` and clamped to
  ``bounds.buffer_k``. A flash crowd raises K (bigger, smoother server
  steps); a quiet night shrinks it (no waiting on reports that are not
  coming).
- **async flush deadline**: same tail tracker as the sync deadline,
  against ``bounds.flush_deadline_s``.

Quantized inputs, quantized outputs: the latency quantiles are
*histogram-bucket upper edges* over the window since the previous
decision (never the cumulative distribution -- a long sunny day must
not blind the controller to the night), so small timing noise lands on
the same bucket edge and the decision stream stays stable; outputs are
rounded (seconds to 1 ms, eps to 1e-4) so repeated runs compare
bitwise. Empty windows (round 0, or nothing arrived) hold every knob:
the controller never steps on no evidence, and never steps outside the
operator bounds (both pinned in tests/test_steering.py).

Thread model: the controller itself is lock-free by design -- every
distributed call site invokes it under the owning server's
``_advance_lock`` (one decision point per round turnover / flush), and
the simulation path is single-threaded.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from fedml_tpu.observability.registry import get_registry


def _clamp(value, lo_hi):
    lo, hi = lo_hi
    return min(max(value, lo), hi)


def _parse_pair(text, cast):
    lo, hi = (cast(x) for x in str(text).split(","))
    if lo > hi:
        raise ValueError(f"bounds pair {text!r}: min exceeds max")
    return (lo, hi)


@dataclass(frozen=True)
class PaceBounds:
    """Operator-set hard bounds; the controller never steps outside
    them, for any knob, under any observation stream (pinned in
    tests/test_steering.py::TestBounds)."""

    buffer_k: tuple = (1, 4096)
    flush_deadline_s: tuple = (0.05, 120.0)
    deadline_s: tuple = (0.05, 120.0)
    overselect: tuple = (0.0, 1.0)

    def intersect(self, outer: "PaceBounds") -> "PaceBounds":
        """The per-tier clamp of the federation tree: a tier's own
        bounds intersected with the coordinator's, so an edge
        controller can never steer a knob outside what the coordinator
        would allow itself (topology/: one controller per edge reads
        its own tier's histograms, but the decision envelope is the
        root's). A knob whose ranges do not overlap collapses to the
        outer bound's nearest edge -- the coordinator wins."""
        def _meet(mine, theirs):
            lo = max(mine[0], theirs[0])
            hi = min(mine[1], theirs[1])
            if lo > hi:  # disjoint: the outer (coordinator) range wins
                return (theirs[0], theirs[1])
            return (lo, hi)
        return PaceBounds(
            buffer_k=_meet(self.buffer_k, outer.buffer_k),
            flush_deadline_s=_meet(self.flush_deadline_s,
                                   outer.flush_deadline_s),
            deadline_s=_meet(self.deadline_s, outer.deadline_s),
            overselect=_meet(self.overselect, outer.overselect))


@dataclass(frozen=True)
class PaceDecision:
    """One control decision (all knobs, even the unchanged ones)."""

    index: int
    buffer_k: int
    flush_deadline_s: float
    deadline_s: float
    overselect: float
    reason: str     # dominant rule this decision: hold | track-tail |
    #                 abandon-backoff | track-loss | track-arrival (comma-
    #                 joined when several moved)
    inputs: dict = field(default_factory=dict)

    def record(self, prefix="pace/") -> dict:
        return {prefix + "decision": self.index,
                prefix + "buffer_k": self.buffer_k,
                prefix + "flush_deadline_s": self.flush_deadline_s,
                prefix + "deadline_s": self.deadline_s,
                prefix + "overselect": self.overselect,
                prefix + "reason": self.reason}


#: Histograms the controller windows over (name -> obs key stem).
_WATCHED_HISTOGRAMS = (("fed_report_latency_seconds", "latency"),
                       ("fed_staleness_levels", "staleness"),
                       ("fed_buffer_depth_levels", "depth"))


def _window_quantile(edges, window_counts, q):
    """Quantile over a *delta* histogram (bucket counts since the last
    decision): the upper edge of the first bucket whose cumulative
    window count reaches ``q * total`` -- same conservative rule as
    ``MetricsRegistry.histogram_quantile`` (never under-reports a
    tail). None on an empty window."""
    total = sum(window_counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for le, c in zip(edges, window_counts):
        cum += c
        if cum >= target:
            return float(le)
    return math.inf


class PaceController:
    """Deterministic closed-loop pace controller (module docstring).

    One instance steers one server (or one simulated run): it carries
    the current knob values and the per-histogram window state. Call
    :meth:`observe_registry` to snapshot the live distributions, then
    :meth:`decide` once per round turnover / buffer flush.
    """

    def __init__(self, bounds: Optional[PaceBounds] = None, seed: int = 0,
                 buffer_k: int = 64, flush_deadline_s: float = 1.0,
                 deadline_s: float = 1.0, overselect: float = 0.0,
                 latency_margin: float = 1.25, step_up: float = 2.0,
                 step_down: float = 4.0, abandon_backoff: float = 3.0,
                 fill_fraction: float = 0.8, overselect_safety: float = 1.25,
                 overselect_max_delta: float = 0.5):
        self.bounds = bounds if bounds is not None else PaceBounds()
        self.seed = int(seed)
        self.latency_margin = float(latency_margin)
        self.step_up = float(step_up)
        self.step_down = float(step_down)
        self.abandon_backoff = float(abandon_backoff)
        self.fill_fraction = float(fill_fraction)
        self.overselect_safety = float(overselect_safety)
        self.overselect_max_delta = float(overselect_max_delta)
        # starting points are the operator's configured knobs, clamped
        # into the operator's own bounds (a start outside them is a
        # config contradiction resolved toward the bounds)
        self.buffer_k = int(_clamp(int(buffer_k), self.bounds.buffer_k))
        self.flush_deadline_s = float(_clamp(float(flush_deadline_s),
                                             self.bounds.flush_deadline_s))
        self.deadline_s = float(_clamp(float(deadline_s),
                                       self.bounds.deadline_s))
        self.overselect = float(_clamp(float(overselect),
                                       self.bounds.overselect))
        self.decisions = []
        self._hist_last = {}  # histogram name -> last cumulative counts

    @classmethod
    def from_args(cls, args) -> Optional["PaceController"]:
        """``--pace_steering`` switchboard: None when the flag is off
        (the disabled path is exactly today's code)."""
        if not int(getattr(args, "pace_steering", 0) or 0):
            return None
        bounds = PaceBounds(
            buffer_k=_parse_pair(
                getattr(args, "pace_k_bounds", "1,4096"), int),
            flush_deadline_s=_parse_pair(
                getattr(args, "pace_flush_bounds", "0.05,120"), float),
            deadline_s=_parse_pair(
                getattr(args, "pace_deadline_bounds", "0.05,120"), float),
            overselect=_parse_pair(
                getattr(args, "pace_overselect_bounds", "0,1"), float))
        return cls(
            bounds, seed=int(getattr(args, "seed", 0) or 0),
            buffer_k=int(getattr(args, "buffer_k", 64) or 64),
            flush_deadline_s=float(getattr(args, "flush_deadline", 0.0)
                                   or 1.0),
            deadline_s=float(getattr(args, "deadline", 0.0) or 1.0),
            overselect=float(getattr(args, "overselect", 0.0) or 0.0))

    # -- observation --------------------------------------------------------
    def observe_registry(self, reg=None) -> dict:
        """Snapshot the registry distributions as *windowed* statistics:
        p50/p90 of each watched histogram over the counts accumulated
        since this controller's previous snapshot, plus the rolling
        rounds/hour gauge. Returns {} when the registry is off or the
        windows are empty -- :meth:`decide` holds on missing keys."""
        if reg is None:
            reg = get_registry()
        if reg is None:
            return {}
        obs = {}
        for name, stem in _WATCHED_HISTOGRAMS:
            snap = reg.histogram_buckets(name)
            if snap is None:
                continue
            edges, counts = snap
            last = self._hist_last.get(name)
            if last is not None and len(last) == len(counts):
                window = [c - p for c, p in zip(counts, last)]
            else:
                window = list(counts)
            self._hist_last[name] = counts
            for q, tag in ((0.5, "p50"), (0.9, "p90")):
                v = _window_quantile(edges, window, q)
                if v is not None:
                    obs[f"{stem}_{tag}"] = v
        rph = reg.get("fed_rounds_per_hour")
        if isinstance(rph, (int, float)) and math.isfinite(rph):
            obs["rounds_per_hour"] = float(rph)
        return obs

    # -- the law ------------------------------------------------------------
    def _track_tail(self, current, p90, bounds):
        """Move ``current`` toward ``latency_margin * p90``, geometric-
        rate-limited, clamped. Returns (new, moved)."""
        if p90 is None or not math.isfinite(p90) or p90 <= 0:
            return current, False
        target = _clamp(self.latency_margin * p90, bounds)
        new = _clamp(target, (current / self.step_down,
                              current * self.step_up))
        new = round(_clamp(new, bounds), 3)
        return new, new != current

    def decide(self, outcome=None, selected=None, reporting=None,
               arrival_rate=None, flush_reason=None, flush_clients=None,
               obs=None) -> PaceDecision:
        """One control decision.

        Args (every one optional -- the law only moves knobs it has
        evidence for):
          outcome: last sync round outcome ("complete" | "degraded" |
            "abandoned").
          selected / reporting: last cohort size vs reports aggregated
            (feeds the over-selection loss tracker).
          arrival_rate: reports/second folded over the last flush
            window (feeds the async buffer-K sizing).
          flush_reason / flush_clients: the last async flush's reason
            and client count (a below-K deadline flush corroborates a
            shrinking K).
          obs: :meth:`observe_registry` snapshot (windowed quantiles).
        """
        obs = dict(obs or {})
        p90 = obs.get("latency_p90")
        reasons = []

        # report deadline (sync rounds). An abandon with ZERO reports is
        # a latency signal (nothing beat the deadline: back off before
        # re-tracking); an abandon WITH reports is a loss signal (the
        # cohort starved below quorum -- the over-selection tracker
        # below is the right actuator, and lengthening the deadline
        # would just make the starved re-run more expensive).
        if outcome == "abandoned" and not reporting:
            self.deadline_s = round(
                _clamp(self.deadline_s * self.abandon_backoff,
                       self.bounds.deadline_s), 3)
            reasons.append("abandon-backoff")
        else:
            self.deadline_s, moved = self._track_tail(
                self.deadline_s, p90, self.bounds.deadline_s)
            if moved:
                reasons.append("track-tail")

        # async flush deadline: same tail tracker, its own bounds
        self.flush_deadline_s, moved = self._track_tail(
            self.flush_deadline_s, p90, self.bounds.flush_deadline_s)
        if moved and "track-tail" not in reasons:
            reasons.append("track-tail")

        # over-selection: track the observed loss odds
        if selected and reporting is not None and selected > 0:
            loss = _clamp(1.0 - float(reporting) / float(selected),
                          (0.0, 1.0))
            target = _clamp(self.overselect_safety * loss
                            / max(1.0 - loss, 1e-6),
                            self.bounds.overselect)
            delta = _clamp(target - self.overselect,
                           (-self.overselect_max_delta,
                            self.overselect_max_delta))
            new = round(_clamp(self.overselect + delta,
                               self.bounds.overselect), 4)
            if new != self.overselect:
                self.overselect = new
                reasons.append("track-loss")

        # async buffer K: what actually arrives within one flush window
        if arrival_rate is not None and arrival_rate > 0:
            target = _clamp(arrival_rate * self.flush_deadline_s
                            * self.fill_fraction, self.bounds.buffer_k)
            new = _clamp(target, (self.buffer_k / self.step_down,
                                  self.buffer_k * self.step_up))
            new = int(_clamp(int(round(new)), self.bounds.buffer_k))
            if new != self.buffer_k:
                self.buffer_k = new
                reasons.append("track-arrival")

        dec = PaceDecision(
            index=len(self.decisions), buffer_k=self.buffer_k,
            flush_deadline_s=self.flush_deadline_s,
            deadline_s=self.deadline_s, overselect=self.overselect,
            reason=",".join(reasons) if reasons else "hold",
            inputs={"outcome": outcome, "selected": selected,
                    "reporting": reporting, "arrival_rate": arrival_rate,
                    "flush_reason": flush_reason,
                    "flush_clients": flush_clients, **obs})
        self.decisions.append(dec)
        self._emit(dec)
        return dec

    def _emit(self, dec: PaceDecision):
        """Decision series into the metrics registry (no-op when off).
        The ``reason`` label is drawn from the law's fixed vocabulary,
        never per-client identity (fedlint FL115)."""
        reg = get_registry()
        if reg is None:
            return
        reg.set_gauge("fed_pace_deadline_seconds", dec.deadline_s,
                      help="steered sync report deadline")
        reg.set_gauge("fed_pace_flush_deadline_seconds",
                      dec.flush_deadline_s,
                      help="steered async flush deadline")
        reg.set_gauge("fed_pace_buffer_k", dec.buffer_k,
                      help="steered async buffer K")
        reg.set_gauge("fed_pace_overselect", dec.overselect,
                      help="steered cohort over-selection eps")
        reg.inc("fed_pace_decisions_total",
                help="pace-steering decisions by dominant rule",
                reason=dec.reason)

    # -- reporting ----------------------------------------------------------
    def status_fields(self) -> dict:
        """The ``pace`` block for a server's status.json snapshot."""
        out = {"decisions": len(self.decisions),
               "buffer_k": self.buffer_k,
               "flush_deadline_s": self.flush_deadline_s,
               "deadline_s": self.deadline_s,
               "overselect": self.overselect}
        if self.decisions:
            out["last_reason"] = self.decisions[-1].reason
        return out

    def record(self, prefix="pace/") -> dict:
        """Metrics-record fragment of the latest decision (rides round
        records on steered runs, like the async/* counters)."""
        if not self.decisions:
            return {prefix + "decision": -1}
        return self.decisions[-1].record(prefix)


def add_steering_args(parser):
    parser.add_argument(
        "--pace_steering", type=int, default=0,
        help="closed-loop pace steering (Bonawitz MLSys'19 S3, "
             "resilience/steering.py): the server adapts --buffer_k / "
             "--flush_deadline / --deadline / --overselect per decision "
             "from its own live report-latency/staleness/buffer-depth "
             "histograms, within the --pace_*_bounds. Default off; off "
             "is bitwise-identical to today (switchboard discipline). "
             "On these mains it steers the simulation's over-selection "
             "(needs --overselect or --straggler_p to arm the sampling "
             "loop); the distributed servers take a PaceController via "
             "their pace_controller= parameter")
    parser.add_argument(
        "--pace_k_bounds", type=str, default="1,4096",
        help="pace steering: min,max async buffer K")
    parser.add_argument(
        "--pace_flush_bounds", type=str, default="0.05,120",
        help="pace steering: min,max async flush deadline seconds")
    parser.add_argument(
        "--pace_deadline_bounds", type=str, default="0.05,120",
        help="pace steering: min,max sync report deadline seconds")
    parser.add_argument(
        "--pace_overselect_bounds", type=str, default="0,1",
        help="pace steering: min,max over-selection eps")
    return parser


__all__ = ["PaceBounds", "PaceDecision", "PaceController",
           "add_steering_args"]
