"""Round-granular server recovery: kill -9 at round k, resume at round k.

``utils/checkpoint.py`` already round-trips the full round-loop state
(model pytree, server aux state, both RNG streams, round index) through
orbax; this module is the thin resilience-facing layer over it:

- :class:`RoundRecovery` snapshots *every* completed round (or every
  ``save_every``) and restores the latest on construction of a restarted
  server, counting ``resumes`` for the metrics record.
- The determinism contract (docs/RESILIENCE.md): with no faults firing,
  a server killed after round k and restarted with ``--resume`` produces a
  bitwise-identical round-(k+1..n) trajectory, because every input to
  round k+1 -- params, server aux, the jax PRNG key, the host data-RNG
  bit-generator state, and the round counter -- is restored exactly, and
  cohort selection is a pure function of the round index
  (``client_sampling`` reseeds per round; ``attempt`` folds in for
  abandoned-round re-runs).

The distributed server FSM (``integration.ResilientFedAvgServer``) stores
numpy weight pytrees; the simulation path (``FedAvgAPI`` via
``experiments/common.run_fedavg_family``) stores jax pytrees -- orbax
handles both, and restore hands back numpy that callers re-place.
"""

from __future__ import annotations

import logging
from typing import Optional

from fedml_tpu.utils.checkpoint import Checkpointer


class RoundRecovery:
    """Per-round snapshot/restore for a federated server.

    Args:
      directory: checkpoint root (orbax layout, shared with the
        ``--checkpoint_dir`` flag).
      save_every: snapshot cadence in rounds (1 = every round, the
        resilience default -- a control-plane server's state is a few MB
        of weights, and losing rounds to a crash costs more than the
        write).
      max_to_keep: orbax GC horizon.
    """

    def __init__(self, directory: str, save_every: int = 1, max_to_keep: int = 3,
                 warmup_fn=None):
        # synchronous saves: round turnover happens on whichever transport
        # serve thread delivered the last report, and orbax's async
        # finalize thread cannot be handed between threads
        self._ckpt = Checkpointer(directory, max_to_keep=max_to_keep,
                                  async_save=False)
        self.save_every = max(1, int(save_every))
        self.resumes = 0
        self.saves = 0
        # fedwarm hook (fedml_tpu.compile.warm_restart partial): invoked
        # after a successful restore so the recovered server AOT-reloads
        # its round executables from the persistent compilation cache
        # BEFORE re-entering the round loop -- the Bonawitz requirement
        # that a restarted server must not stall the fleet recompiling
        self.warmup_fn = warmup_fn
        self.last_warmup = None

    def maybe_save(self, round_idx: int, global_state, server_state=(),
                   rng=None, data_rng=None, last: bool = False) -> bool:
        """Snapshot round ``round_idx`` when on cadence (or ``last``)."""
        if round_idx % self.save_every and not last:
            return False
        self._ckpt.save(round_idx, global_state, server_state=server_state,
                        rng=rng, data_rng=data_rng)
        self.saves += 1
        return True

    def restore_latest(self, server_state_template=None) -> Optional[dict]:
        """Latest snapshot as ``{"global_state","server_state","rng",
        "data_rng","round_idx"}``, or None on a fresh directory. Counts a
        resume only when something was actually restored."""
        kw = ({} if server_state_template is None
              else {"server_state_template": server_state_template})
        saved = self._ckpt.restore(**kw)
        if saved is None:
            return None
        self.resumes += 1
        logging.info("resilience: resuming from round %d snapshot",
                     saved["round_idx"])
        if self.warmup_fn is not None:
            self.warm_restart()  # stores its report in self.last_warmup
        return saved

    def warm_restart(self):
        """Run the configured warmup hook now (also called automatically
        after a successful :meth:`restore_latest` when ``warmup_fn`` is
        set). Returns the fedwarm report, or None without a hook."""
        if self.warmup_fn is None:
            return None
        report = self.warmup_fn()
        self.last_warmup = report
        logging.info("resilience: warm restart -- %s programs, %.2fs, "
                     "%s cache hits / %s misses",
                     report.get("warmup/programs"),
                     report.get("warmup/seconds", 0.0),
                     report.get("warmup/cache_hits"),
                     report.get("warmup/cache_misses"))
        return report

    def latest_round(self) -> Optional[int]:
        return self._ckpt.latest_round()

    def close(self):
        self._ckpt.close()


__all__ = ["RoundRecovery"]
