"""Wiring: resilience policies into the algorithms, transports, and mains.

Three integration surfaces:

1. **Simulation path** (``FedAvgAPI``/``FedOptAPI`` and everything built on
   them): :class:`SimResilience` implements over-selection + simulated
   deadline misses for the vmapped/sharded rounds. The engine already
   weights the aggregate by per-client sample counts over the *packed
   cohort*, so restricting the cohort to the reporting subset IS the
   renormalized partial aggregate -- no aggregation math changes, and the
   empty-cohort fail-fast (``engine.py:325``) stays in force.
2. **Distributed control plane**: :class:`ResilientFedAvgServer` /
   :class:`ResilientFedAvgClient` FSMs run deadline-based partial
   aggregation with retryable sends over any ``BaseCommunicationManager``
   (local, tcp, mqtt), with optional per-round crash recovery.
   :func:`run_tcp_fedavg` drives a whole multi-rank scenario in one
   process -- the chaos smoke in ``scripts/ci.sh`` and
   ``tests/test_resilience.py`` both use it.
3. **Flags**: :func:`add_resilience_args` contributes ``--deadline`` /
   ``--overselect`` / ``--quorum`` / ``--straggler_p`` to the FedAvg-family
   mains (``--resume`` already exists on the checkpoint side).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

import numpy as np

from fedml_tpu.core.locks import audited_rlock
from fedml_tpu.core.comm.base import MSG_TYPE_PEER_JOIN, MSG_TYPE_PEER_LOST
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message
from fedml_tpu.compression.wire import (
    WIRE_DELTA_KEY, WIRE_SPEC_KEY, CompressedUpdate, ef_step, encode_rng,
    host_compressor)
from fedml_tpu.observability.perfmon import get_perf_monitor
from fedml_tpu.observability.tracing import get_tracer
from fedml_tpu.program import RoundProgram
from fedml_tpu.program.cohort import CohortPolicy, client_sampling
from fedml_tpu.program.cohort import sample_ranks as _program_sample_ranks
from fedml_tpu.resilience.policy import (
    ROUND_DEGRADED, RetryPolicy, RoundController, RoundPolicy,
    send_with_retry)

MSG_S2C_SYNC = "res_sync"        # server -> client: params, round, attempt
MSG_C2S_REPORT = "res_report"    # client -> server: params (plain) OR
# cdelta+compressor (compressed update delta), n, round, attempt


def add_resilience_args(parser):
    parser.add_argument(
        "--deadline", type=float, default=0.0,
        help="per-round report deadline in seconds for the distributed "
             "control plane (0 = wait for every report, the reference's "
             "block-on-slowest behavior). Simulation rounds have no wall "
             "clock; there --straggler_p models deadline misses")
    parser.add_argument(
        "--overselect", type=float, default=0.0,
        help="over-selection eps (Bonawitz MLSys'19 S3): select "
             "ceil((1+eps)*C) clients, aggregate the first C reports")
    parser.add_argument(
        "--quorum", type=float, default=0.5,
        help="minimum reporting fraction of the aggregation target for a "
             "deadline-bounded round to complete (degraded); below it the "
             "round is abandoned and re-run with a fresh cohort")
    parser.add_argument(
        "--straggler_p", type=float, default=0.0,
        help="simulation only: per-(round, client) probability of missing "
             "the report deadline, drawn from a seeded stream keyed on "
             "(seed, round, attempt, client) -- reproducible chaos for the "
             "vmapped rounds")
    parser.add_argument(
        "--transport", type=str, default="tcp",
        choices=("tcp", "eventloop"),
        help="distributed control-plane transport: 'tcp' = the thread-"
             "per-client hub (core/comm/tcp.py, honest at tens of "
             "ranks), 'eventloop' = the single-threaded selector event "
             "loop (fedml_tpu.net.eventloop: connection multiplexing, "
             "write-queue backpressure with slow-peer shedding -- the "
             "10k-connection path). Same FSMs, same wire schema. On "
             "these mains the flag is configuration only (their rounds "
             "are simulated; no transport is opened) -- pass the value "
             "through to the distributed drivers' transport= parameter "
             "(run_tcp_fedavg / run_async_tcp_fedavg / run_fanin_fedavg)"
             " when driving a real multi-rank run")
    parser.add_argument(
        "--race_audit", type=int, default=0,
        help="arm the concurrency race sanitizer "
             "(fedml_tpu.analysis.runtime.race_audit): control-plane "
             "locks record acquisition order and held-while-blocking "
             "events; the report (race/lock_order_cycles, "
             "race/held_while_blocking, ...) goes to the metrics sink")
    return parser


class SimResilience:
    """Over-selection + seeded deadline-miss simulation for the sim rounds.

    ``sample(round_idx, total, per_round)`` replaces the bare
    ``client_sampling`` call: it over-selects, removes simulated deadline
    misses, keeps the first C survivors ("first C reports win"), and
    re-runs below-quorum rounds with a fresh cohort (attempt folded into
    the sampling seed). Cumulative counters ride every round's metrics
    record so degraded rounds are visible in summary.json.
    """

    def __init__(self, policy: RoundPolicy, straggler_p: float = 0.0,
                 seed: int = 0, miss_fn=None):
        self.policy = policy
        self.straggler_p = float(straggler_p)
        self.seed = int(seed)
        self._miss_fn = miss_fn
        self.rounds_degraded = 0
        self.rounds_abandoned = 0
        self.clients_dropped = 0

    @classmethod
    def from_args(cls, args) -> Optional["SimResilience"]:
        over = float(getattr(args, "overselect", 0.0) or 0.0)
        sp = float(getattr(args, "straggler_p", 0.0) or 0.0)
        if over <= 0 and sp <= 0:
            return None
        policy = CohortPolicy(overselect=over,
                              quorum=float(getattr(args, "quorum", 0.5)))
        return cls(policy, straggler_p=sp,
                   seed=int(getattr(args, "seed", 0)))

    def sample(self, round_idx, client_num_in_total, client_num_per_round):
        """Returns ``(reporting_client_ids, round_record_dict)``."""
        with get_tracer().span("cohort-select", round=int(round_idx)) as sp:
            reporting, record = self._sample(
                round_idx, client_num_in_total, client_num_per_round)
            sp.set(selected=record["res/selected"],
                   reporting=record["res/reporting"],
                   attempts=record["res/attempts"])
            return reporting, record

    def misses_deadline(self, round_idx, attempt, client_id) -> bool:
        if self._miss_fn is not None:
            return bool(self._miss_fn(round_idx, attempt, client_id))
        if self.straggler_p <= 0:
            return False
        # keyed (not sequential) stream: order-independent, reproducible
        rng = np.random.default_rng(
            (self.seed, int(round_idx), int(attempt), int(client_id)))
        return bool(rng.random() < self.straggler_p)

    def _sample(self, round_idx, client_num_in_total, client_num_per_round):
        target = min(client_num_per_round, client_num_in_total)
        for attempt in range(self.policy.max_round_retries + 1):
            selected = client_sampling(
                round_idx, client_num_in_total,
                self.policy.select_count(target, client_num_in_total),
                attempt=attempt)
            # seeded permutation before the "first C win" trim: when
            # select_count reaches the total, client_sampling's
            # all-clients early-return is an ORDERED range, and trimming
            # that untouched would hand the lowest ids every round (a
            # silently biased cohort). The permutation models report
            # arrival order; the final subset is sorted so the packed
            # cohort (and thus the aggregate) has one canonical order.
            perm = np.random.default_rng(
                (self.seed, int(round_idx), int(attempt))).permutation(
                    len(selected))
            selected = [selected[i] for i in perm]
            reporting = [c for c in selected
                         if not self.misses_deadline(round_idx, attempt, c)]
            dropped = len(selected) - len(reporting)
            if len(reporting) >= self.policy.quorum_count(target):
                reporting = sorted(reporting[:target])
                self.clients_dropped += dropped
                degraded = len(reporting) < target
                self.rounds_degraded += int(degraded)
                return reporting, {
                    "res/selected": len(selected),
                    "res/reporting": len(reporting),
                    "res/degraded": int(degraded),
                    "res/attempts": attempt + 1,
                    "res/rounds_degraded": self.rounds_degraded,
                    "res/rounds_abandoned": self.rounds_abandoned,
                    "res/clients_dropped": self.clients_dropped,
                }
            # below quorum: abandon, re-run with a fresh cohort
            self.rounds_abandoned += 1
            self.clients_dropped += dropped
            logging.warning(
                "round %d attempt %d: %d/%d reports is below quorum %d -- "
                "abandoning and re-sampling", round_idx, attempt,
                len(reporting), len(selected),
                self.policy.quorum_count(target))
        raise RuntimeError(
            f"round {round_idx}: abandoned "
            f"{self.policy.max_round_retries + 1} consecutive attempts "
            "(straggler rate incompatible with the quorum; lower --quorum "
            "or --straggler_p)")


class ResilientFedAvgClient(ClientManager):
    """Client FSM: on sync, run local training and report.

    ``local_train_fn(params, round_idx, rank) -> (params, num_samples)``
    over numpy pytrees. A lost server ends the loop cleanly (there is
    nobody left to report to; the default fail-fast would raise out of a
    worker thread instead).

    ``compressor`` (spec string, e.g. ``"qsgd"``/``"topk:0.01"``) arms
    wire compression: the report ships the compressed update DELTA
    (``cdelta`` + ``compressor`` keys) instead of full params. Biased
    compressors (topk/signsgd) carry an error-feedback residual -- a
    plain per-client host accumulator owned by this FSM object (the
    process IS the stable rank, so the accumulator survives shed/rejoin
    cycles of OTHER ranks and re-keyed cohort slots can never
    cross-contaminate it; same shape as the jax-free soak swarm's);
    unbiased qsgd runs feedback-free (``wire.ef_step``'s rule -- see
    compression/wire.py for the measured instability feedback causes
    there). ``None``/``"none"`` keeps today's plain-``params`` report,
    byte-for-byte.
    """

    def __init__(self, args, comm, rank, size, local_train_fn,
                 retry_policy: Optional[RetryPolicy] = None,
                 compressor=None, dp=None):
        super().__init__(args, comm, rank=rank, size=size)
        self.local_train_fn = local_train_fn
        self.retry_policy = retry_policy
        self.compressor = host_compressor(compressor)
        # client-side DP leg (program/privacy.py DPPolicy or None): the
        # trained params are privatized (clip -> seeded noise on the
        # delta) BEFORE anything touches the report -- the raw update
        # never crosses the trust boundary
        self.dp = dp
        self._ef_residual = None  # zero accumulator until first report
        self.counters = {"retries": 0}

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_S2C_SYNC, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_PEER_LOST,
                                              self._on_server_lost)

    def _on_sync(self, msg):
        # spans parent under the server's round span: the SYNC message
        # carries its context (__trace__), and the manager dispatch loop
        # made it this thread's current parent before calling us
        tracer = get_tracer()
        rnd = int(msg.get("round"))
        with tracer.span("local-train", rank=self.rank, round=rnd):
            params, n = self.local_train_fn(msg.get("params"), rnd,
                                            self.rank)
        with tracer.span("report", rank=self.rank, round=rnd):
            out = Message(MSG_C2S_REPORT, self.rank, 0)
            attempt = int(msg.get("attempt"))
            if self.dp is not None:
                # DP before codec, always: the mechanism's clip->noise
                # runs on the raw delta, then the (lossy, NON-private)
                # uplink encode sees only the privatized update --
                # fedcheck FL153 pins this order statically
                params = self.dp.privatize_params(
                    msg.get("params"), params, self.rank, rnd, attempt)
            if self.compressor is None:
                out.add("params", params)
            else:
                enc = self._compress_update(msg.get("params"), params,
                                            rnd, attempt)
                out.add(WIRE_DELTA_KEY, enc)
                out.add(WIRE_SPEC_KEY, self.compressor.spec)
            out.add("num_samples", float(n))
            out.add("round", rnd)
            out.add("attempt", attempt)
            tracer.inject(out)  # stitch the server's report handling here
            try:
                if self.retry_policy is not None:
                    send_with_retry(self.com_manager, out,
                                    self.retry_policy,
                                    counters=self.counters)
                else:
                    self.send_message(out)
            except (ConnectionError, OSError):
                # server gone mid-report; the peer-lost path ends the loop
                logging.warning("rank %d: report send failed (server "
                                "lost?)", self.rank)

    def _compress_update(self, base, params, rnd, attempt):
        """EF-compress ``params - base`` for the uplink. The residual is
        this object's own host accumulator (this process IS the stable
        rank -- no device traffic in the report hot path); the encode
        rng is keyed (rank, round, attempt) so two runs over the same
        schedule encode bit-identically."""
        base = {k: np.asarray(v, np.float32) for k, v in base.items()}
        delta = {k: np.asarray(params[k], np.float32) - base[k]
                 for k in base}
        enc, _decoded, self._ef_residual = ef_step(
            self.compressor, delta, self._ef_residual,
            encode_rng((self.rank, rnd, attempt)))
        return enc

    def _on_server_lost(self, msg):
        # sender is the LOST rank: only rank 0 dying concerns a client.
        # On the local transport a killed sibling's PEER_LOST fans out to
        # every mailbox -- that must not collapse the healthy federation.
        if int(msg.get_sender_id()) != 0:
            logging.info("rank %d: sibling rank %s lost (ignored)",
                         self.rank, msg.get_sender_id())
            return
        logging.warning("rank %d: server lost -- stopping", self.rank)
        self.finish()


class ResilientFedAvgServer(ServerManager):
    """Rank-0 FSM: over-selection, report deadline, partial aggregation,
    abandoned-round re-runs, and per-round crash recovery.

    Args:
      init_params: initial global weights (numpy pytree).
      rounds: total federated rounds.
      round_policy / retry_policy: see ``resilience.policy``.
      client_ns: optional ``{rank: num_samples}`` override for weighting
        (otherwise reports carry their own ``num_samples``).
      cohort_target: aggregation target C (default: all clients).
      cohort_override: ``fn(round_idx, attempt) -> [ranks]`` forcing the
        cohort (the A/B harness replays a faulted run's reporting subsets).
      recovery: ``RoundRecovery`` for per-round snapshots + resume.
      metrics_logger: per-round records (``res/*`` counters; wire bytes
        attach via the transport's ``count_wire`` feed when wired).
    """

    def __init__(self, args, comm, size, init_params, rounds,
                 round_policy: RoundPolicy,
                 retry_policy: Optional[RetryPolicy] = None,
                 cohort_target: Optional[int] = None, cohort_override=None,
                 recovery=None, metrics_logger=None, pace_controller=None,
                 dp=None, robust=None):
        super().__init__(args, comm, rank=0, size=size)
        self.params = {k: np.asarray(v) for k, v in init_params.items()}
        self.rounds = int(rounds)
        # the ONE RoundProgram this server executes: the caller's policy
        # is the program's cohort leg, and every cohort draw / report
        # fold goes through its jax-free host view (the sim engine
        # lowers the same program via compile_sim -- the conformance
        # suite pins the two consumers equal). round_policy stays the
        # live steered attribute; _steer_locked re-replaces the program.
        # dp rides the program for the manifest + epsilon accounting
        # (the mechanism itself is client-side); robust swaps the fold.
        self.program = RoundProgram(cohort=round_policy, dp=dp,
                                    robust=robust)
        self._host = self.program.host_view()
        self.round_policy = round_policy
        self.retry_policy = retry_policy or RetryPolicy()
        self.cohort_target = cohort_target
        self.cohort_override = cohort_override
        self.recovery = recovery
        self.metrics_logger = metrics_logger
        self.alive = set(range(1, size))
        self.round_idx = 0
        self.attempt = 0
        self.failed = None  # set to a reason string on unrecoverable stop
        self.history = []          # per-round aggregated params
        self.reporting_log = []    # per-round sorted reporting ranks
        self.counters = {"rounds_degraded": 0, "rounds_abandoned": 0,
                         "clients_dropped": 0, "clients_rejoined": 0,
                         "clients_resumed": 0, "retries": 0, "resumes": 0}
        # closed-loop pace steering (resilience/steering.py): when armed,
        # every round decision re-derives deadline_s/overselect from the
        # windowed report-latency tail + observed loss fraction, within
        # operator bounds. None = today's fixed-policy path, bit for bit.
        self.pace = pace_controller
        self._last_selected = 0  # last cohort size (over-selection incl.)
        self._last_target = 0    # last aggregation target C -- the loss
        # denominator the controller tracks (reports short of C is the
        # shortfall over-selection exists to cover; selected/C would
        # read surplus over-selection itself as loss and ratchet)
        self._controller = RoundController(
            round_policy, self._on_round_complete, self._on_round_abandoned)
        # one detached span per round attempt (begun at _open_round on the
        # turnover thread, ended at the decision on a serve/timer thread);
        # its context rides every SYNC so client spans stitch under it
        self._round_span = None
        # perf-monitor state (all guarded by _advance_lock; written only
        # while a monitor is armed): attempt-open wall time for the
        # report-latency/straggler-tail histogram, last decision outcome
        # + counts for status.json, and the decision's unconsumed round
        # duration for the rounds/hour pace gauge
        self._round_t0 = None
        self._last_outcome = None
        self._outcomes = {"complete": 0, "degraded": 0, "abandoned": 0}
        self._pending_round_dt = None
        # serializes round turnover and guards `alive`. Sync sends happen
        # OUTSIDE this lock (_open_round returns them, _send_syncs
        # delivers) so a blocking write to a wedged peer can never pin
        # the deadline/abandon machinery. RLock as defense in depth: a
        # failed unlocked send dispatches PEER_LOST synchronously on the
        # sending thread, and that chain may re-enter a turnover callback
        # (depth bounded by max_round_retries -- the abandon path is the
        # only recursive one, since zero reports can never meet quorum).
        self._advance_lock = audited_rlock()

    # -- FSM surface -------------------------------------------------------
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_C2S_REPORT,
                                              self._on_report)
        self.register_message_receive_handler(MSG_TYPE_PEER_LOST,
                                              self._on_peer_lost)
        self.register_message_receive_handler(MSG_TYPE_PEER_JOIN,
                                              self._on_peer_join)

    def start(self):
        """Kick off round 0 (or the checkpointed round on resume).

        The restore runs UNDER ``_advance_lock``: ``run_tcp_fedavg``
        starts client threads before the server FSM, so a racing send
        failure can dispatch PEER_LOST (and drive a turnover) while the
        restore is still rewriting ``params``/``round_idx`` -- writing
        them unlocked races those handler threads (fedcheck FL123)."""
        syncs, span = [], None
        with self._advance_lock:
            if self.recovery is not None:
                saved = self.recovery.restore_latest()
                if saved is not None:
                    self.params = {k: np.asarray(v)
                                   for k, v in saved["global_state"].items()}
                    self.round_idx = int(saved["round_idx"])
                    self.counters["resumes"] += 1
            done = self.round_idx >= self.rounds
            if not done:
                syncs = self._open_round()
                span = self._round_span
            done = done or self.failed is not None
        # finish() OUTSIDE the lock: it reaches the transport's STOP wave
        # (blocking per-peer socket writes) and must not pin the turnover
        # lock every handler needs. The class-local static FL125 cannot
        # see this cross-class chain; fedcheck FL126 (crossclass.py) now
        # catches it at lint time -- reverting this shape is the pinned
        # mutation fixture -- and the race sanitizer's
        # held-while-blocking check remains the runtime backstop
        if done:
            self.finish()
            return
        self._send_syncs(syncs, span)

    def _open_round(self):
        """Open the next round attempt: sample the cohort and arm the
        controller. Runs UNDER ``_advance_lock``; returns the sync
        messages for :meth:`_send_syncs` to deliver OUTSIDE the lock --
        a blocking ``sendall`` to a wedged-but-alive client (full send
        buffer, keepalives still ACKed) must never pin the lock the
        deadline/abandon machinery needs."""
        alive = sorted(self.alive)
        if not alive:
            self._fail("every client is lost")
            return []
        target = min(self.cohort_target or len(alive), len(alive))
        if self.cohort_override is not None:
            cohort = list(self.cohort_override(self.round_idx, self.attempt))
            target = min(target, len(cohort))
        else:
            cohort = self._host.sample_ranks(
                self.round_idx, self.attempt, alive,
                self._host.select_count(target, len(alive)))
        self._last_selected = len(cohort)
        self._last_target = target
        self._controller.begin(self.round_idx, self.attempt, cohort, target)
        self._round_t0 = (time.time()
                          if get_perf_monitor() is not None else None)
        tracer = get_tracer()
        self._round_span = tracer.start_span(
            "round", root=True, rank=0, round=self.round_idx,
            attempt=self.attempt, cohort=len(cohort), target=target)
        syncs = []
        for r in cohort:
            m = Message(MSG_S2C_SYNC, 0, r)
            m.add("params", self.params)
            m.add("round", self.round_idx)
            m.add("attempt", self.attempt)
            tracer.inject(m, self._round_span.context)
            syncs.append((r, m))
        return syncs

    def _send_syncs(self, syncs, span=None):
        """Deliver the opened round's syncs (no locks held). A send that
        outlives its round attempt (deadline fired mid-delivery and a new
        attempt opened) is harmless: the message carries its (round,
        attempt) tag and stale reports land in the late counter. ``span``
        is the caller's under-lock snapshot of the round span
        (``self._round_span`` mutates under ``_advance_lock``; reading it
        here would race the turnover threads -- fedcheck FL123)."""
        if not syncs:
            return
        with get_tracer().span(
                "broadcast", parent=None if span is None else span.context,
                n=len(syncs)):
            for _r, m in syncs:
                try:
                    send_with_retry(self.com_manager, m, self.retry_policy,
                                    counters=self.counters)
                except (ConnectionError, OSError):
                    pass  # peer-lost dispatch already told the controller

    def _on_report(self, msg):
        mon = get_perf_monitor()
        if mon is not None:
            with self._advance_lock:  # _round_t0 mutates under the lock
                # only reports for the CURRENTLY open (round, attempt)
                # are measured against its t0: a straggler whose round
                # already turned over would otherwise be clocked against
                # the NEW round's open and land in a LOW bucket --
                # inverting the straggler tail for exactly the events it
                # exists to capture (those land in the late counter)
                t0 = (self._round_t0
                      if (int(msg.get("round")) == self.round_idx
                          and int(msg.get("attempt")) == self.attempt)
                      else None)
            if t0 is not None:
                # round-open -> report latency: the distribution whose
                # upper buckets are the straggler tail (observed outside
                # the lock -- the registry has its own)
                mon.observe_report_latency(time.time() - t0)
        # parents under the client's "report" span (context injected into
        # the report message, adopted by the manager dispatch loop)
        with get_tracer().span("report-recv",
                               rank=int(msg.get_sender_id()),
                               round=int(msg.get("round"))):
            self._controller.report(
                msg.get("round"), msg.get("attempt"), msg.get_sender_id(),
                msg.get("num_samples"), self._report_payload(msg))

    def _report_payload(self, msg):
        """Plain reports stay numpy param dicts; a compressed report
        (``cdelta``) becomes a :class:`CompressedUpdate` against the
        OPEN round's params -- read under ``_advance_lock``, which also
        serializes round turnover, so whenever the controller accepts
        the report (round/attempt match) the captured base IS the model
        that round broadcast; a mismatched base only ever pairs with a
        report the controller rejects as late. The fold decodes-and-
        folds the delta sparsely (O(k) for topk) at the turnover -- the
        hub relayed the payload on a header peek and nothing densified
        it per report."""
        enc = msg.get(WIRE_DELTA_KEY)
        if enc is None:
            return {k: np.asarray(v) for k, v in msg.get("params").items()}
        with self._advance_lock:
            base = self.params
        return CompressedUpdate(enc=enc, spec=str(msg.get(WIRE_SPEC_KEY)),
                                base=base, base_key=0)

    def _on_peer_lost(self, msg):
        rank = int(msg.get_sender_id())
        # alive mutates under _advance_lock: _open_round reads it
        # (sorted) on the turnover thread, and mutating a set
        # mid-iteration raises. controller.peer_lost runs OUTSIDE the
        # lock: it can fire a turnover callback, and those must never
        # inherit a held _advance_lock (their _send_syncs runs unlocked
        # by design -- see _open_round).
        with self._advance_lock:
            if rank in self.alive:
                self.alive.discard(rank)
                self.counters["clients_dropped"] += 1
                logging.warning("server: client rank %d lost "
                                "(%d alive)", rank, len(self.alive))
        self._controller.peer_lost(rank)

    # -- round turnover (serve/timer threads) ------------------------------
    def _on_round_complete(self, reports, outcome):
        syncs, span = [], None
        tracer = get_tracer()
        with self._advance_lock:
            rspan = self._round_span
            with tracer.span(
                    "aggregate",
                    parent=None if rspan is None else rspan.context,
                    reports=len(reports)):
                # base = the params this round broadcast (read before
                # the assignment rebinds them): the robust norm-clip
                # fold clips each report's delta against exactly the
                # model the cohort trained on
                self.params, _total = self._host.fold_reports(
                    reports, base=self.params)
            if rspan is not None:
                rspan.set(outcome=outcome, reports=len(reports)).end()
            self.history.append(dict(self.params))
            self.reporting_log.append(sorted(reports))
            degraded = outcome == ROUND_DEGRADED
            self.counters["rounds_degraded"] += int(degraded)
            self._last_outcome = outcome
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
            if self._round_t0 is not None:
                self._pending_round_dt = time.time() - self._round_t0
            self._log_round(len(reports), degraded)
            if self.recovery is not None:
                done = self.round_idx + 1 >= self.rounds
                self.recovery.maybe_save(self.round_idx + 1, self.params,
                                         last=done)
            self.round_idx += 1
            self.attempt = 0
            done = self.round_idx >= self.rounds
            if not done:
                if self.pace is not None:
                    self._steer_locked(outcome, len(reports))
                syncs = self._open_round()
                span = self._round_span
            done = done or self.failed is not None
        if done:                    # see start(): no STOP wave under the
            self.finish()           # turnover lock
            self._report_health()
            return
        self._send_syncs(syncs, span)
        self._report_health()

    def _on_round_abandoned(self, reports):
        syncs, span = [], None
        with self._advance_lock:
            rspan = self._round_span
            if rspan is not None:
                rspan.set(outcome="abandoned", reports=len(reports)).end()
            self.counters["rounds_abandoned"] += 1
            self._last_outcome = "abandoned"
            self._outcomes["abandoned"] += 1
            logging.warning("round %d attempt %d abandoned with %d reports",
                            self.round_idx, self.attempt, len(reports))
            self.attempt += 1
            if self.attempt > self.round_policy.max_round_retries:
                self._fail(f"round {self.round_idx} abandoned "
                           f"{self.attempt} times")
            else:
                if self.pace is not None:
                    # abandon-backoff: the re-run attempt opens with a
                    # longer deadline, not the one that just starved
                    self._steer_locked("abandoned", len(reports))
                syncs = self._open_round()
                span = self._round_span
            done = self.failed is not None
        if done:  # see start(): finish() outside the lock
            self.finish()
            self._report_health()
            return
        self._send_syncs(syncs, span)
        self._report_health()

    def _steer_locked(self, outcome, n_reports):
        """One pace decision per round turnover (runs UNDER
        ``_advance_lock``). The decided deadline/overselect replace the
        frozen ``RoundPolicy`` on both the server and the controller, so
        the NEXT ``begin()`` arms the steered deadline."""
        dec = self.pace.decide(outcome=outcome,
                               selected=self._last_target,
                               reporting=min(n_reports, self._last_target),
                               obs=self.pace.observe_registry())
        if (dec.deadline_s != self.round_policy.deadline_s
                or dec.overselect != self.round_policy.overselect):
            self.round_policy = dataclasses.replace(
                self.round_policy, deadline_s=dec.deadline_s,
                overselect=dec.overselect)
            # the program IS the round definition: steering evolves it
            # (pure-data replace) so host-view cohort math reads the
            # live knobs, not the ones the server was constructed with
            self.program = self.program.replace(cohort=self.round_policy)
            self._host = self.program.host_view()
            self._controller.policy = self.round_policy
            logging.info("server: pace steering -> deadline %.3fs, "
                         "overselect %.3f (%s)", dec.deadline_s,
                         dec.overselect, dec.reason)

    def _on_peer_join(self, msg):
        """Rejoin protocol: a previously shed/lost rank's fresh HELLO
        was accepted by the transport -- re-admit it to the alive set so
        the next ``_open_round`` can sample it, AND resume it into the
        round in flight: the rank is admitted to the open attempt's
        cohort (:meth:`RoundController.admit`) and handed the current
        model with the round's (round, attempt) context, so it
        contributes *this* round instead of idling to the next.
        Re-admission shipped first (the alive-set half); this is the
        work-resumption half -- ``clients_resumed`` counts the ranks
        that actually got mid-round work. The resume never extends the
        round: the target is unchanged, the deadline stays armed, and a
        resumed rank that stays silent costs nothing over-selection
        would not already cover."""
        rank = int(msg.get_sender_id())
        sync = None
        with self._advance_lock:
            if self.failed is not None or rank in self.alive:
                logging.info("server: peer-join for rank %d ignored "
                             "(already alive or run failed)", rank)
                return
            self.alive.add(rank)
            self.counters["clients_rejoined"] += 1
            if self._controller.admit(self.round_idx, self.attempt, rank):
                self.counters["clients_resumed"] += 1
                m = Message(MSG_S2C_SYNC, 0, rank)
                m.add("params", self.params)
                m.add("round", self.round_idx)
                m.add("attempt", self.attempt)
                rspan = self._round_span
                get_tracer().inject(
                    m, None if rspan is None else rspan.context)
                sync = m
        if sync is not None:
            logging.warning("server: rank %d rejoined -- resumed into "
                            "round %d attempt %d", rank,
                            int(sync.get("round")), int(sync.get("attempt")))
            # delivered OUTSIDE the lock, same discipline as _send_syncs
            try:
                send_with_retry(self.com_manager, sync, self.retry_policy,
                                counters=self.counters)
            except (ConnectionError, OSError):
                pass  # peer-lost dispatch already told the controller
        else:
            logging.warning("server: rank %d rejoined -- eligible from "
                            "the next cohort", rank)
        self._report_health()

    def _report_health(self):
        """Status.json + round-pace snapshot for the perf monitor --
        called from the turnover/serve threads AFTER ``_advance_lock``
        is released (the status write is file I/O; the snapshot takes
        the lock only briefly). No-op when the monitor is off."""
        mon = get_perf_monitor()
        if mon is None:
            return
        with self._advance_lock:
            fields = {
                "server": "resilient",
                "round": self.round_idx,
                "attempt": self.attempt,
                "rounds_total": self.rounds,
                "last_outcome": ("failed" if self.failed is not None
                                 else self._last_outcome),
                "outcome_counts": dict(self._outcomes),
                "alive_ranks": sorted(self.alive),
                "clients_dropped": self.counters["clients_dropped"],
                "clients_resumed": self.counters["clients_resumed"],
            }
            if self.pace is not None:
                fields["pace"] = self.pace.status_fields()
            # the active round definition (steering replaces it mid-run):
            # an operator reading status.json sees WHICH program the
            # fleet is executing, not just how fast
            fields["program"] = self.program.manifest()
            dt, self._pending_round_dt = self._pending_round_dt, None
        if dt is not None:
            mon.observe_round(dt)
        rph = mon.rounds_per_hour()
        if rph is not None:
            # the one pace metric both paradigms report (async feeds it
            # flush-to-flush): steered-vs-fixed comparisons read this
            fields["rounds_per_hour"] = rph
        mon.status_update(force=True, **fields)  # decision-rate writes:
        # one per round attempt, never a hot path

    def _log_round(self, n_reports, degraded):
        if self.metrics_logger is None:
            return
        rec = {"round": self.round_idx, "res/reports": n_reports,
               "res/degraded": int(degraded)}
        if self.program.dp is not None:
            # epsilon accounting rides every round record: the round
            # being logged is the (round_idx + 1)-th completed release
            rec.update(self.program.dp.record(self.round_idx + 1))
        rec.update({f"res/{k}": v for k, v in self.counters.items()})
        rec.update({f"res/{k}": v
                    for k, v in self._controller.counters.items()})
        if self.pace is not None:
            rec.update(self.pace.record())
        self.metrics_logger(rec)

    def _fail(self, reason):
        """Mark the run failed and stop the controller. Runs UNDER
        ``_advance_lock``; the lock-exiting caller performs the actual
        ``finish()`` (transport STOP wave = blocking writes) outside."""
        self.failed = reason
        if self._round_span is not None:
            # an attempt left open by an unrecoverable stop still records
            # (Span.end is idempotent: a decided round already ended it)
            self._round_span.set(outcome="failed").end()
        logging.error("resilient server giving up: %s", reason)
        self._controller.cancel()

    def finish(self):
        self._controller.cancel()
        super().finish()


def _sample_ranks(round_idx, attempt, ranks, k):
    """Seeded-by-(round, attempt) cohort over explicit rank ids -- the
    program's :func:`~fedml_tpu.program.cohort.sample_ranks` under its
    historical name (kept for callers/tests that import it from here).
    Shares the :func:`~fedml_tpu.program.cohort.attempt_seed` fold with
    ``client_sampling`` so both paths draw agreeing cohorts for the same
    (round, attempt)."""
    return _program_sample_ranks(round_idx, attempt, ranks, k)


def quadratic_trainer(lr=0.25):
    """Deterministic 'local training' oracle for control-plane scenarios:
    one gradient-descent step on ``0.5 * ||w - t_rank||^2`` where the
    target is a fixed function of the rank. Real GD arithmetic, bitwise
    reproducible, rank-distinguishable -- the chaos smoke's A/B oracle."""

    def train(params, round_idx, rank):
        out = {}
        for k in sorted(params):
            w = np.asarray(params[k], np.float32)
            target = np.full_like(w, np.float32(rank))
            out[k] = w + np.float32(lr) * (target - w)
        return out, float(10 * rank)

    return train


def run_tcp_fedavg(world_size, rounds, round_policy, init_params,
                   fault_plan=None, retry_policy=None, cohort_target=None,
                   cohort_override=None, trainer=None, recovery=None,
                   metrics_logger=None, host="localhost", port=None,
                   timeout=60.0, join_timeout=90.0, transport="tcp",
                   pace_controller=None, late_clients=(),
                   decode_workers=1, compressor=None, dp=None,
                   robust=None):
    """Drive a full multi-rank TCP FedAvg scenario in one process.

    Clients run in daemon threads (rank r wrapped by ``fault_plan`` when
    given); the server FSM runs its receive loop on the caller thread.
    ``transport`` selects the byte layer (``--transport``: "tcp" =
    thread-per-client hub, "eventloop" = selector loop) -- the FSMs are
    identical either way. ``pace_controller`` arms closed-loop pace
    steering on the server (``--pace_steering``); ``late_clients`` is a
    list of ``(rank, delay_s)`` re-dials exercising the rejoin protocol
    (a fresh unfaulted client HELLOing back in after its original
    incarnation was killed or shed). ``compressor`` (e.g. ``"qsgd"``)
    arms wire compression on every client: reports ship compressed
    deltas (error feedback on the biased compressors) and the server
    folds them sparsely against the round's base (``None``/``"none"`` =
    today's plain reports, byte-identical). ``dp`` (a
    ``program.DPPolicy``) privatizes every client's update delta
    (clip -> per-(rank, round, attempt) seeded noise) before the uplink
    encode, and rides the server's program for manifest + epsilon
    accounting; ``robust`` (a ``program.RobustPolicy``) swaps the
    server fold for the leg's robust variant.
    Returns the server (``.history``, ``.reporting_log``, ``.counters``,
    ``.failed``). Used by the ci.sh chaos/steering/compression smokes
    and test_resilience.py / test_net.py / test_steering.py.
    """
    import socket

    from fedml_tpu.core.comm.tcp import TcpCommManager
    from fedml_tpu.net.eventloop import EventLoopCommManager

    if port is None:
        s = socket.socket()
        s.bind((host, 0))
        port = s.getsockname()[1]
        s.close()
    trainer = trainer or quadratic_trainer()
    # transport construction stays INLINE (no factory indirection):
    # fedcheck's cross-class pass (FL126) types `com_manager` from
    # constructor-argument flow at instantiation sites, and a
    # factory-returned local is untyped -- these bindings are what keep
    # BOTH transports inside every FSM's held-lock chain analysis
    evloop = transport == "eventloop"

    def run_client(rank, delay_s=0.0, faulted=True):
        if delay_s:
            time.sleep(delay_s)
        try:
            if evloop:
                comm = EventLoopCommManager(host, port, rank, world_size,
                                            timeout=timeout)
            else:
                comm = TcpCommManager(host, port, rank, world_size,
                                      timeout=timeout)
        except OSError:
            # a late re-dial can race teardown: nothing left to rejoin
            logging.warning("rank %d: (re)dial failed -- server gone?",
                            rank)
            return
        if faulted and fault_plan is not None:
            comm = fault_plan.wrap(comm, rank)
        fsm = ResilientFedAvgClient(None, comm, rank, world_size, trainer,
                                    compressor=compressor, dp=dp)
        fsm.run()

    threads = [threading.Thread(target=run_client, args=(r,), daemon=True,
                                name=f"res-client-{r}")
               for r in range(1, world_size)]
    threads += [threading.Thread(target=run_client, args=(r, d, False),
                                 daemon=True, name=f"res-rejoin-{r}")
                for r, d in late_clients]
    for t in threads:
        t.start()
    if evloop:
        comm = EventLoopCommManager(host, port, 0, world_size,
                                    timeout=timeout,
                                    metrics_logger=metrics_logger,
                                    decode_workers=decode_workers)
    else:
        comm = TcpCommManager(host, port, 0, world_size, timeout=timeout,
                              metrics_logger=metrics_logger)
    server = ResilientFedAvgServer(
        None, comm, world_size, init_params, rounds, round_policy,
        retry_policy=retry_policy, cohort_target=cohort_target,
        cohort_override=cohort_override, recovery=recovery,
        metrics_logger=metrics_logger, pace_controller=pace_controller,
        dp=dp, robust=robust)
    server.register_message_receive_handlers()
    server.start()
    if server.round_idx < server.rounds and server.failed is None:
        loop = threading.Thread(target=server.com_manager
                                .handle_receive_message, daemon=True,
                                name="res-server-loop")
        loop.start()
        loop.join(timeout=join_timeout)
        if loop.is_alive():
            server.com_manager.stop_receive_message()
            loop.join(timeout=10.0)
            raise TimeoutError(
                f"resilient server hung past {join_timeout}s "
                f"(round {server.round_idx}, failed={server.failed})")
    else:
        # resume found nothing to do (or start() already failed):
        # release the connected clients
        server.com_manager.stop_receive_message()
    for t in threads:
        t.join(timeout=10.0)
    return server


__all__ = ["MSG_S2C_SYNC", "MSG_C2S_REPORT", "add_resilience_args",
           "SimResilience", "ResilientFedAvgClient", "ResilientFedAvgServer",
           "quadratic_trainer", "run_tcp_fedavg"]
