"""FedBuff-style buffered asynchronous aggregation (Nguyen et al.,
AISTATS 2022), composed with this repo's partial-aggregation substrate.

The synchronous control plane (``policy.RoundController``) is a barrier:
a round holds the server until ``target`` reports (or the deadline)
arrive. At population scale the barrier IS the bottleneck -- Bonawitz et
al. (MLSys 2019 §3) already pace-steer around it, and FedBuff removes it:
the server folds client updates into a buffer *as they arrive*,
staleness-weighted, and applies a server update every K folds (or on a
flush deadline). No client ever waits on a straggler; a straggler's
late update still counts, just down-weighted by how many server versions
it missed.

Design notes, in decreasing order of importance:

- **Determinism over arrival order.** :meth:`BufferedAggregator.flush`
  folds the buffered entries through
  :func:`~fedml_tpu.program.aggregation.fold_entries_fp64` -- the same
  sorted-key float64 fold ``aggregate_reports`` uses -- NOT in arrival
  order. Two runs that buffer the same entries flush bitwise-identical
  results no matter how the reports raced. This is also what makes the
  correctness oracle exact: with an infinite flush deadline, staleness
  decay 0 (weight 1) and ``buffer_k`` = cohort size, one flush IS
  ``aggregate_reports`` of the same reports, bit for bit.
- **Staleness weighting** is polynomial (FedBuff's ``1/sqrt(1+s)`` is
  the ``staleness_decay=0.5`` point): an update born at server version
  ``v0`` and folded at ``v`` carries weight multiplier
  ``(1 + (v - v0)) ** -staleness_decay``.
- **Flush-time re-sync** (distributed FSM): a reporting client receives
  the next model when its contribution is *consumed* by a flush, not
  immediately on report. Fast clients therefore cycle in windows of K
  without ever waiting for stragglers (barrier-free), while each client
  contributes at most one update per flush window -- which keeps the
  oracle settings exactly equivalent to the synchronous round and keeps
  "degraded" meaningful without a barrier: a deadline flush below K is
  the async analog of a degraded round (see docs/RESILIENCE.md).

The sim path (``parallel/engine.py BucketedStreamRunner``) feeds the same
aggregator with PRE-WEIGHTED bucket-chunk partial sums (``preweighted=
True``): a chunk dispatched at version ``v0`` and folded after later
flushes is a stale cohort slice, exactly the semantics a real async
population shows, simulated on one chip.

The aggregation machinery itself now lives in
:mod:`fedml_tpu.program.aggregation` (the ``RoundProgram`` subsystem's
aggregation leg): ``AsyncAggPolicy`` is the program's
``AggregationPolicy`` and ``BufferedAggregator`` / ``staleness_weight``
/ ``FlushResult`` are re-exported here under their historical names.
This module keeps the distributed FSM
(:class:`AsyncBufferedFedAvgServer`), which drives its program's
jax-free host view for every fold.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

import numpy as np

from fedml_tpu.core.locks import audited_rlock
from fedml_tpu.core.comm.base import MSG_TYPE_PEER_JOIN, MSG_TYPE_PEER_LOST
from fedml_tpu.core.message import Message
from fedml_tpu.core.managers import ServerManager
from fedml_tpu.compression.wire import (
    WIRE_DELTA_KEY, WIRE_SPEC_KEY, CompressedUpdate)
from fedml_tpu.observability.perfmon import get_perf_monitor
from fedml_tpu.observability.tracing import get_tracer
from fedml_tpu.program import RoundProgram
from fedml_tpu.program.aggregation import (  # noqa: F401 (re-export)
    AggregationPolicy as AsyncAggPolicy, BufferedAggregator, FlushResult,
    staleness_weight)
from fedml_tpu.resilience.policy import RetryPolicy, send_with_retry

# the async server speaks the SAME message schema as the synchronous FSM
# (ResilientFedAvgClient is reused unchanged); import the types from the
# integration module so fedcheck's pairing pass sees one vocabulary
from fedml_tpu.resilience.integration import (  # noqa: F401 (re-export)
    MSG_C2S_REPORT, MSG_S2C_SYNC, ResilientFedAvgClient)


def add_async_args(parser):
    parser.add_argument(
        "--async_agg", type=int, default=0,
        help="FedBuff-style buffered async aggregation (Nguyen et al. "
             "2022): fold client updates as they arrive, staleness-"
             "weighted, server update every --buffer_k folds -- no round "
             "barrier. On these mains it runs the single-chip simulation "
             "(composes with --bucket_edges; --mesh is rejected); the "
             "distributed FSM is AsyncBufferedFedAvgServer, driven "
             "programmatically via run_async_tcp_fedavg")
    parser.add_argument(
        "--buffer_k", type=int, default=64,
        help="async aggregation: client updates per server update "
             "(FedBuff's K)")
    parser.add_argument(
        "--staleness_decay", type=float, default=0.5,
        help="async aggregation: polynomial staleness exponent a -- an "
             "update s server-versions stale is weighted (1+s)**-a "
             "(0 = no discount, 0.5 = FedBuff's 1/sqrt(1+s))")
    parser.add_argument(
        "--flush_deadline", type=float, default=0.0,
        help="async aggregation: wall-clock bound from a window's first "
             "buffered update to its flush (0 = flush only on K); a "
             "deadline flush below K is the async analog of a degraded "
             "round")
    parser.add_argument(
        "--async_window", type=int, default=4,
        help="simulation: in-flight bucket chunks before the oldest is "
             "folded (the simulated client concurrency; staleness "
             "appears when --buffer_k flushes land inside the window)")
    parser.add_argument(
        "--bucket_edges", type=str, default=None,
        help="bucketed ragged streaming for the simulation rounds: "
             "'geometric' (power-of-two local-step bucket edges) or an "
             "explicit comma list e.g. '8,16,32'. Clients are bucketed "
             "by local-step count, masked-and-padded only within their "
             "bucket, and streamed through one compiled program per "
             "bucket shape -- the cohort axis is unbounded "
             "(parallel/engine.py BucketedStreamRunner)")
    return parser


class AsyncBufferedFedAvgServer(ServerManager):
    """Rank-0 barrier-free FSM: buffered async aggregation over the same
    SYNC/REPORT schema as :class:`ResilientFedAvgServer` (clients run the
    unchanged :class:`ResilientFedAvgClient`).

    Protocol: every alive client gets the v0 model; each report is folded
    into the :class:`BufferedAggregator` with staleness = current version
    minus the version the client trained on; a flush (K clients buffered,
    or the flush deadline) produces server version v+1 and re-syncs
    exactly the flush's contributors with the new model. Fast clients
    cycle in windows of K; stragglers' late reports fold into a later
    window, staleness-discounted. The run ends after ``total_updates``
    flushes.

    Under the oracle settings (no deadline, decay 0, K >= clients) each
    flush collects every alive client exactly once and is bitwise
    ``aggregate_reports`` -- the trajectory equals the synchronous
    server's, which the A/B test pins.
    """

    def __init__(self, args, comm, size, init_params, total_updates,
                 async_policy: AsyncAggPolicy,
                 retry_policy: Optional[RetryPolicy] = None,
                 metrics_logger=None, timer_factory=threading.Timer,
                 pace_controller=None, dp=None, robust=None):
        super().__init__(args, comm, rank=0, size=size)
        self.params = {k: np.asarray(v) for k, v in init_params.items()}
        self.total_updates = int(total_updates)
        self.async_policy = async_policy
        self.retry_policy = retry_policy or RetryPolicy()
        self.metrics_logger = metrics_logger
        # the ONE RoundProgram this server executes: the caller's policy
        # is the program's aggregation leg, and the buffered aggregator
        # plus every fold go through its jax-free host view (the sim
        # engine lowers the same program via compile_sim -- the
        # conformance suite pins the two consumers equal)
        # dp rides the program for the manifest + epsilon accounting
        # (the mechanism is client-side); an armed robust leg swaps the
        # aggregator's flush fold (make_aggregator wires it through --
        # norm_clip is sync-only and rejected there).
        self.program = RoundProgram(aggregation=async_policy, dp=dp,
                                    robust=robust)
        self._host = self.program.host_view()
        self.agg = self._host.make_aggregator()
        self.alive = set(range(1, size))
        self.failed = None
        self.history = []     # params after each flush
        self.flush_log = []   # per-flush sorted contributor ranks
        self.counters = {"reports": 0, "late_reports": 0,
                         "clients_dropped": 0, "clients_rejoined": 0,
                         "retries": 0, "stale_base_reports": 0}
        # compressed-report base retention: version -> the params that
        # version issued. A compressed report born at version v decodes
        # against base v (its delta is relative to the model the client
        # trained on), so a base stays retained while any alive rank's
        # last sync is still <= that version (_rank_version records the
        # version each sync carried). Buffered CompressedUpdate entries
        # hold their OWN base reference -- pruning here can never
        # invalidate an already-accepted report, only force a
        # stale_base drop of a report nobody should still be sending.
        self._bases = {0: self.params}
        self._rank_version = {}
        # closed-loop pace steering (resilience/steering.py): when armed,
        # each flush re-decides buffer_k/flush_deadline from the live
        # arrival rate + windowed latency tail, within operator bounds.
        # None = today's fixed-knob path, bit for bit.
        self.pace = pace_controller
        self._pace_window_t = time.time()   # flush-window open (arrival
        self._pace_window_reports = 0       # rate feed; _advance_lock)
        self._timer_factory = timer_factory
        self._timer = None
        self._last_flush_reason = None
        self._window_t0 = None       # wall time the current flush window
        # opened (start / previous flush): the async analog of the sync
        # server's per-attempt t0, feeding fed_report_latency_seconds so
        # the straggler-tail evidence is transport- AND paradigm-agnostic
        self._prev_flush_t = None    # wall time of the previous flush
        self._pending_flush_dts = []  # flush-to-flush seconds, unconsumed
        # (a list, drained by _report_health: back-to-back flushes on
        # different handler threads must not overwrite each other's
        # sample -- the slow interval is exactly the one pace wants)
        # serializes version turnover/alive/params; all sends happen
        # OUTSIDE it (same discipline as ResilientFedAvgServer: a
        # blocking write to a wedged peer must never pin the lock the
        # fold/flush machinery needs -- fedcheck FL125/FL126)
        self._advance_lock = audited_rlock()

    # -- FSM surface -------------------------------------------------------
    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_C2S_REPORT,
                                              self._on_report)
        self.register_message_receive_handler(MSG_TYPE_PEER_LOST,
                                              self._on_peer_lost)
        self.register_message_receive_handler(MSG_TYPE_PEER_JOIN,
                                              self._on_peer_join)

    def start(self):
        with self._advance_lock:
            if get_perf_monitor() is not None:
                self._window_t0 = time.time()
            syncs = [self._make_sync_locked(r) for r in sorted(self.alive)]
            done = self.total_updates <= 0 or not self.alive
        if done:  # finish() = transport STOP wave, never under the lock
            self.finish()
            return
        self._send_syncs(syncs)

    def _make_sync_locked(self, rank):
        m = Message(MSG_S2C_SYNC, 0, rank)
        m.add("params", self.params)
        m.add("round", self.agg.version)
        m.add("attempt", 0)  # schema parity with the synchronous client
        self._rank_version[rank] = self.agg.version
        return m

    def _report_payload_locked(self, msg):
        """Plain reports stay numpy param dicts; a compressed report
        (``cdelta``) becomes a :class:`CompressedUpdate` against the
        base of the version the client trained on (``round`` in the
        report = the version its sync carried). The fold decodes-and-
        folds the delta sparsely (O(k) for topk) at flush time, and
        each distinct base version is densified exactly once per flush
        -- never per report. Returns None when the base was pruned (a
        report no live sync should still produce): the caller drops it
        into ``stale_base_reports``."""
        enc = msg.get(WIRE_DELTA_KEY)
        if enc is None:
            return {k: np.asarray(v) for k, v in msg.get("params").items()}
        born = int(msg.get("round"))
        base = self._bases.get(born)
        if base is None:
            return None
        return CompressedUpdate(enc=enc, spec=str(msg.get(WIRE_SPEC_KEY)),
                                base=base, base_key=born)

    def _prune_bases_locked(self):
        """Drop base versions no alive rank can still report against
        (every alive rank's last sync is newer). The current version is
        always retained -- a rejoin syncs it next."""
        floor = min((self._rank_version.get(r, 0) for r in self.alive),
                    default=self.agg.version)
        for v in [v for v in self._bases
                  if v < floor and v < self.agg.version]:
            del self._bases[v]

    def _send_syncs(self, syncs):
        for m in syncs:
            try:
                send_with_retry(self.com_manager, m, self.retry_policy,
                                counters=self.counters)
            except (ConnectionError, OSError):
                pass  # peer-lost dispatch already updated `alive`

    # -- handler threads ---------------------------------------------------
    def receive_message_batch(self, msg_type, msgs):
        """Batched dispatch from a chunk-draining transport (the event
        loop): a run of reports folds under ONE ``_advance_lock``
        acquisition via :meth:`BufferedAggregator.fold_many`, with the
        flush boundary landing on exactly the report it would land on
        one message at a time -- trajectories are bitwise-identical to
        the per-message path (A/B-pinned). Any other type -- and any
        run while the tracer is armed (per-message ``__trace__``
        contexts must parent each handler) -- takes the default
        per-message loop."""
        if str(msg_type) != MSG_C2S_REPORT or len(msgs) < 2 \
                or get_tracer().enabled:
            super().receive_message_batch(msg_type, msgs)
            return
        self._on_report_batch(msgs)

    def _on_report_batch(self, msgs):
        mon = get_perf_monitor()
        syncs, done = [], False
        with self._advance_lock:
            reports = []
            for msg in msgs:
                if self.failed is not None \
                        or self.agg.version >= self.total_updates:
                    self.counters["late_reports"] += 1
                    logging.info("async server: late report from rank %d "
                                 "(run already finished)",
                                 int(msg.get_sender_id()))
                    continue
                # payload/weight/sender converted ONCE per report --
                # only staleness depends on the flush segment
                payload = self._report_payload_locked(msg)
                if payload is None:
                    self.counters["stale_base_reports"] += 1
                    logging.warning(
                        "async server: compressed report from rank %d "
                        "against pruned base version %d -- dropped",
                        int(msg.get_sender_id()), int(msg.get("round")))
                    continue
                reports.append((
                    int(msg.get_sender_id()), float(msg.get("num_samples")),
                    payload, int(msg.get("round"))))
            i = 0
            while i < len(reports) and not done:
                # staleness (and the latency window origin) is constant
                # within a segment: both only move at a flush, which
                # ends the segment
                version = self.agg.version
                t0 = self._window_t0
                entries = [(r, w, p, max(0, version - born))
                           for r, w, p, born in reports[i:]]
                consumed, _depth = self.agg.fold_many(
                    entries, ready_target=len(self.alive))
                if mon is not None and t0 is not None:
                    # the per-report window-open -> report latency the
                    # unbatched handler observes
                    now = time.time()
                    for _ in range(consumed):
                        mon.observe_report_latency(now - t0)
                i += consumed
                self.counters["reports"] += consumed
                if self.pace is not None:
                    self._pace_window_reports += consumed
                if self.agg.ready(target=len(self.alive)):
                    done, more = self._flush_locked("buffer_k")
                    if not done:
                        # per-message parity: a NON-final flush's syncs
                        # are sent (below, outside the lock); the
                        # finishing flush's syncs are dropped exactly as
                        # _on_report drops them
                        syncs.extend(more)
                else:
                    self._arm_deadline_locked()
                if done and i < len(reports):
                    # run finished mid-batch: the rest are late reports
                    self.counters["late_reports"] += len(reports) - i
        if done:
            # syncs accumulated from earlier (non-final) flushes in this
            # batch still go out -- the per-message path sent them before
            # the finishing report was even folded
            self._send_syncs(syncs)
            self.finish()
            self._report_health()
            return
        self._send_syncs(syncs)
        self._report_health()

    def _on_report(self, msg):
        rank = int(msg.get_sender_id())
        mon = get_perf_monitor()
        if mon is not None:
            with self._advance_lock:  # _window_t0 mutates under the lock
                t0 = self._window_t0
            if t0 is not None:
                # window-open -> report latency: the barrier-free analog
                # of the sync server's straggler-tail distribution (a
                # stale report measures against the CURRENT window --
                # that is its true lateness under flush-time re-sync)
                mon.observe_report_latency(time.time() - t0)
        syncs, done = [], False
        with self._advance_lock:
            if self.failed is not None \
                    or self.agg.version >= self.total_updates:
                self.counters["late_reports"] += 1
                logging.info("async server: late report from rank %d "
                             "(run already finished)", rank)
                return
            born = int(msg.get("round"))
            staleness = max(0, self.agg.version - born)
            payload = self._report_payload_locked(msg)
            if payload is None:
                self.counters["stale_base_reports"] += 1
                logging.warning("async server: compressed report from "
                                "rank %d against pruned base version %d "
                                "-- dropped", rank, born)
                return
            depth = self.agg.fold(rank, float(msg.get("num_samples")),
                                  payload, staleness=staleness)
            self.counters["reports"] += 1
            if self.pace is not None:
                self._pace_window_reports += 1
            if self.agg.ready(target=len(self.alive)):
                done, syncs = self._flush_locked("buffer_k")
            else:
                self._arm_deadline_locked()
                logging.debug("async server: buffered report from rank %d "
                              "(depth %d, staleness %d)", rank, depth,
                              staleness)
        if done:
            self.finish()
            self._report_health()
            return
        self._send_syncs(syncs)
        self._report_health()

    def _on_peer_lost(self, msg):
        rank = int(msg.get_sender_id())
        syncs, done = [], False
        with self._advance_lock:
            if (self.failed is not None
                    or self.agg.version >= self.total_updates):
                # teardown race: clients dropping after the final flush
                # must not mark a completed run failed or flush past
                # total_updates
                logging.info("async server: peer-lost for rank %d after "
                             "run end (ignored)", rank)
                return
            if rank in self.alive:
                self.alive.discard(rank)
                self._rank_version.pop(rank, None)
                self._prune_bases_locked()
                self.counters["clients_dropped"] += 1
                logging.warning("async server: client rank %d lost "
                                "(%d alive)", rank, len(self.alive))
            else:
                logging.info("async server: duplicate peer-lost for rank "
                             "%d (already dropped)", rank)
            if not self.alive:
                self.failed = "every client is lost"
                done = True
            elif (self.agg.depth
                  and self.agg.ready(target=len(self.alive))):
                # the lost peer was the one the buffer was waiting on
                done, syncs = self._flush_locked("peer_lost")
        if done:
            self.finish()
            self._report_health()
            return
        self._send_syncs(syncs)
        self._report_health()

    def _on_peer_join(self, msg):
        """Rejoin protocol: a previously shed/lost rank dialed back in
        (fresh transport HELLO). Re-admit it to the alive set and hand
        it the CURRENT model so it contributes from the next flush
        window -- capacity that comes back must not stay dead for the
        run (ROADMAP control-plane follow-up (c))."""
        rank = int(msg.get_sender_id())
        sync = None
        with self._advance_lock:
            if (self.failed is not None
                    or self.agg.version >= self.total_updates):
                logging.info("async server: rank %d rejoined after run "
                             "end (ignored)", rank)
                return
            if rank in self.alive:
                logging.info("async server: duplicate peer-join for rank "
                             "%d (already alive)", rank)
                return
            self.alive.add(rank)
            self.counters["clients_rejoined"] += 1
            sync = self._make_sync_locked(rank)
            logging.warning("async server: rank %d rejoined (%d alive)",
                            rank, len(self.alive))
        self._send_syncs([sync])
        self._report_health()

    def _report_health(self):
        """Push a health snapshot to the perf monitor's status.json (and
        the update-pace histogram) -- called from handler threads AFTER
        ``_advance_lock`` is released (the status write is file I/O; the
        snapshot itself takes the lock only briefly). No-op when the
        monitor is off."""
        mon = get_perf_monitor()
        if mon is None:
            return
        with self._advance_lock:
            fields = {
                "server": "async-buffered",
                "round": self.agg.version,
                "total_updates": self.total_updates,
                "alive_ranks": sorted(self.alive),
                "buffer_depth": self.agg.depth,
                "last_flush_reason": self._last_flush_reason,
                "reports": self.counters["reports"],
                "clients_dropped": self.counters["clients_dropped"],
                "outcome": ("failed" if self.failed is not None else
                            "complete" if self.agg.version
                            >= self.total_updates else "running"),
            }
            if self.pace is not None:
                fields["pace"] = self.pace.status_fields()
            # the active round definition (steering replaces the
            # aggregation leg mid-run): status.json names the program,
            # not just its throughput
            fields["program"] = self.program.manifest()
            dts, self._pending_flush_dts = self._pending_flush_dts, []
        for dt in dts:
            mon.observe_round(dt)  # flush-to-flush pace: the barrier-free
            # "round" time, feeding the rolling rounds/hour gauge
        rph = mon.rounds_per_hour()
        if rph is not None:
            fields["rounds_per_hour"] = rph
        mon.status_update(force=fields["outcome"] != "running", **fields)

    # -- flush machinery (runs UNDER _advance_lock) ------------------------
    def _flush_locked(self, reason):
        self._cancel_timer_locked()
        self._last_flush_reason = reason
        if get_perf_monitor() is not None:
            now = time.time()
            if self._prev_flush_t is not None:
                self._pending_flush_dts.append(now - self._prev_flush_t)
            self._prev_flush_t = now
            self._window_t0 = now  # next window's report-latency origin
        res = self.agg.flush(reason)
        self.params = res.params
        self._bases[res.version] = res.params
        self._prune_bases_locked()
        self.history.append(dict(res.params))
        self.flush_log.append(tuple(sorted(res.contributors)))
        degraded = res.clients < min(self.async_policy.buffer_k,
                                     max(1, len(self.alive)))
        logging.info("async server: flush %d/%d (%s) over %d client(s), "
                     "max staleness %d%s", res.version, self.total_updates,
                     reason, res.clients, res.max_staleness,
                     " [degraded]" if degraded else "")
        if self.pace is not None:
            # closed-loop steering: one decision per flush, AFTER the
            # degraded call above (degraded is judged by the policy the
            # flush actually ran under). Arrival rate = reports folded
            # across the window just closed; the latency/staleness
            # windows come from the registry histograms.
            self._steer_locked(reason, res.clients)
        if self.metrics_logger is not None:
            rec = {"update": res.version, "async/flush_reason": reason,
                   "async/flush_clients": res.clients,
                   "async/flush_degraded": int(degraded)}
            if self.program.dp is not None:
                # epsilon accounting per server release (each flush is
                # one composition step of the Gaussian mechanism)
                rec.update(self.program.dp.record(res.version))
            rec.update(self.agg.record())
            if self.pace is not None:
                rec.update(self.pace.record())
            self.metrics_logger(rec)
        done = res.version >= self.total_updates
        syncs = []
        if not done:
            for r in sorted(set(res.contributors) & self.alive):
                syncs.append(self._make_sync_locked(r))
        return done, syncs

    def _steer_locked(self, flush_reason, flush_clients):
        """One pace decision (runs UNDER ``_advance_lock``; the registry
        reads take only the registry's own lock). The decided
        buffer_k/flush_deadline replace the frozen policy on both the
        server and the aggregator -- ``ready()`` and the deadline timer
        read the new values from the next fold on."""
        now = time.time()
        window_s = max(now - self._pace_window_t, 1e-6)
        rate = self._pace_window_reports / window_s
        self._pace_window_reports = 0
        self._pace_window_t = now
        dec = self.pace.decide(flush_reason=flush_reason,
                               flush_clients=flush_clients,
                               arrival_rate=rate,
                               obs=self.pace.observe_registry())
        if (dec.buffer_k != self.async_policy.buffer_k
                or dec.flush_deadline_s
                != self.async_policy.flush_deadline_s):
            self.async_policy = dataclasses.replace(
                self.async_policy, buffer_k=dec.buffer_k,
                flush_deadline_s=dec.flush_deadline_s)
            # the program IS the round definition: steering evolves it
            # (pure-data replace) so program/host-view readers stay
            # coherent with the live knobs
            self.program = self.program.replace(
                aggregation=self.async_policy)
            self._host = self.program.host_view()
            self.agg.policy = self.async_policy
            logging.info("async server: pace steering -> buffer_k %d, "
                         "flush deadline %.3fs (%s)", dec.buffer_k,
                         dec.flush_deadline_s, dec.reason)

    def _arm_deadline_locked(self):
        if (self.async_policy.flush_deadline_s <= 0
                or self._timer is not None):
            return
        # the timer carries its version generation: a flush cancels it,
        # but a callback already racing the lock must not flush the NEXT
        # window it wakes up inside (same pattern as RoundController)
        self._timer = self._timer_factory(
            self.async_policy.flush_deadline_s, self._on_flush_deadline,
            args=(self.agg.version,))
        self._timer.daemon = True
        self._timer.start()

    def _cancel_timer_locked(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _on_flush_deadline(self, version):
        syncs, done = [], False
        with self._advance_lock:
            self._timer = None
            if (self.failed is not None or version != self.agg.version
                    or self.agg.depth == 0):
                return  # stale generation / already flushed
            done, syncs = self._flush_locked("deadline")
        if done:
            self.finish()
            self._report_health()
            return
        self._send_syncs(syncs)
        self._report_health()

    def finish(self):
        with self._advance_lock:
            self._cancel_timer_locked()
        super().finish()


def run_async_tcp_fedavg(world_size, total_updates, async_policy,
                         init_params, fault_plan=None, retry_policy=None,
                         trainer=None, metrics_logger=None,
                         host="localhost", port=None, timeout=60.0,
                         join_timeout=90.0, transport="tcp",
                         pace_controller=None, late_clients=(),
                         decode_workers=1, compressor=None, dp=None,
                         robust=None):
    """Drive a multi-rank TCP buffered-async FedAvg scenario in one
    process (the async analog of ``integration.run_tcp_fedavg``; clients
    are the unchanged :class:`ResilientFedAvgClient`). ``transport``
    selects the byte layer ("tcp" | "eventloop") with identical FSMs.
    ``pace_controller`` arms closed-loop pace steering on the server;
    ``late_clients`` is a list of ``(rank, delay_s)`` re-dials -- a
    fresh unfaulted client that HELLOs back in after its original
    (usually killed/shed) incarnation, exercising the rejoin protocol.
    ``compressor`` (e.g. ``"qsgd"``/``"topk:0.01"``) arms wire
    compression on every client: reports ship compressed deltas and
    the server folds them sparsely against each report's base version
    (``None``/``"none"`` = today's plain reports, byte-identical).
    Returns the server (``.history``, ``.flush_log``, ``.counters``,
    ``.failed``)."""
    import socket

    from fedml_tpu.core.comm.tcp import TcpCommManager
    from fedml_tpu.net.eventloop import EventLoopCommManager
    from fedml_tpu.resilience.integration import quadratic_trainer

    if port is None:
        s = socket.socket()
        s.bind((host, 0))
        port = s.getsockname()[1]
        s.close()
    trainer = trainer or quadratic_trainer()
    # inline construction, not a factory: see run_tcp_fedavg -- fedcheck
    # FL126 types com_manager from these instantiation sites
    evloop = transport == "eventloop"

    def run_client(rank, delay_s=0.0, faulted=True):
        if delay_s:
            time.sleep(delay_s)
        try:
            if evloop:
                comm = EventLoopCommManager(host, port, rank, world_size,
                                            timeout=timeout)
            else:
                comm = TcpCommManager(host, port, rank, world_size,
                                      timeout=timeout)
        except OSError:
            # a late re-dial can race the run's teardown: nothing to
            # rejoin anymore, which is a legitimate outcome
            logging.warning("rank %d: (re)dial failed -- server gone?",
                            rank)
            return
        if faulted and fault_plan is not None:
            comm = fault_plan.wrap(comm, rank)
        fsm = ResilientFedAvgClient(None, comm, rank, world_size, trainer,
                                    compressor=compressor, dp=dp)
        fsm.run()

    threads = [threading.Thread(target=run_client, args=(r,), daemon=True,
                                name=f"async-client-{r}")
               for r in range(1, world_size)]
    threads += [threading.Thread(target=run_client, args=(r, d, False),
                                 daemon=True, name=f"async-rejoin-{r}")
                for r, d in late_clients]
    for t in threads:
        t.start()
    if evloop:
        comm = EventLoopCommManager(host, port, 0, world_size,
                                    timeout=timeout,
                                    metrics_logger=metrics_logger,
                                    decode_workers=decode_workers)
    else:
        comm = TcpCommManager(host, port, 0, world_size, timeout=timeout,
                              metrics_logger=metrics_logger)
    server = AsyncBufferedFedAvgServer(
        None, comm, world_size, init_params, total_updates, async_policy,
        retry_policy=retry_policy, metrics_logger=metrics_logger,
        pace_controller=pace_controller, dp=dp, robust=robust)
    server.register_message_receive_handlers()
    server.start()
    if server.agg.version < server.total_updates and server.failed is None:
        loop = threading.Thread(target=server.com_manager
                                .handle_receive_message, daemon=True,
                                name="async-server-loop")
        loop.start()
        loop.join(timeout=join_timeout)
        if loop.is_alive():
            server.com_manager.stop_receive_message()
            loop.join(timeout=10.0)
            raise TimeoutError(
                f"async server hung past {join_timeout}s "
                f"(update {server.agg.version}, failed={server.failed})")
    else:
        server.com_manager.stop_receive_message()
    for t in threads:
        t.join(timeout=10.0)
    return server


__all__ = ["AsyncAggPolicy", "BufferedAggregator", "FlushResult",
           "staleness_weight", "add_async_args",
           "AsyncBufferedFedAvgServer", "run_async_tcp_fedavg"]
