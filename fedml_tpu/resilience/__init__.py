"""Fault injection, deadline-based partial aggregation, round recovery.

The reference's distributed paradigm blocks on the slowest MPI rank and
dies with it; production FL at scale is defined by churn (Bonawitz et al.,
*Towards Federated Learning at Scale*, MLSys 2019). This subsystem makes
failure a first-class, *testable* event for the control plane:

- ``faults``      -- deterministic, seeded fault injection over any
                     transport (drop/delay/duplicate/reorder/stall/kill),
                     plus the diurnal trace-driven load generator
                     (day/night arrival swings, correlated dropouts,
                     outages, flash crowds -- replayable JSON traces).
- ``policy``      -- send retry with exponential backoff; over-selection,
                     report deadlines, quorum, round abandonment.
- ``async_agg``   -- FedBuff-style buffered ASYNC aggregation: fold
                     updates as they arrive, staleness-weighted, server
                     update every K folds -- no round barrier.
- ``steering``    -- closed-loop pace steering: the server adapts
                     buffer_k / flush deadline / report deadline /
                     over-selection from its own live histograms, within
                     operator bounds (``--pace_steering``).
- ``recovery``    -- round-granular crash/resume over utils/checkpoint.
- ``integration`` -- wiring into FedAvg-family algorithms, the comm
                     managers, MetricsLogger, and the experiment flags.

Round semantics live OUTSIDE this package: both servers execute a
:class:`fedml_tpu.program.RoundProgram` through its jax-free
``host_view()`` (cohort draws, folds, the buffered aggregator), and
``RoundPolicy`` / ``AsyncAggPolicy`` are aliases of the program's
cohort/aggregation legs -- see docs/PROGRAM.md. This package owns what
is genuinely distributed: transports, retries, deadlines as wall-clock
events, fault injection, steering, recovery.

See docs/RESILIENCE.md for the failure model and determinism contract.
"""

from fedml_tpu.resilience.async_agg import (AsyncAggPolicy,
                                            AsyncBufferedFedAvgServer,
                                            BufferedAggregator,
                                            add_async_args,
                                            run_async_tcp_fedavg,
                                            staleness_weight)
from fedml_tpu.resilience.faults import (ACTIONS, DiurnalTrace, FaultPlan,
                                         FaultRule, FaultyCommManager,
                                         LoadPhase, TraceLoadGen,
                                         TraceShapedCommManager)
from fedml_tpu.resilience.integration import (ResilientFedAvgClient,
                                              ResilientFedAvgServer,
                                              SimResilience,
                                              add_resilience_args,
                                              quadratic_trainer,
                                              run_tcp_fedavg)
from fedml_tpu.resilience.policy import (ROUND_ABANDONED, ROUND_COMPLETE,
                                         ROUND_DEGRADED,
                                         PeerUnreachableError,
                                         RetryPolicy, RoundController,
                                         RoundPolicy, aggregate_reports,
                                         fold_entries_fp64,
                                         send_with_retry)
from fedml_tpu.resilience.recovery import RoundRecovery
from fedml_tpu.resilience.steering import (PaceBounds, PaceController,
                                           PaceDecision, add_steering_args)

__all__ = [
    "ACTIONS", "FaultRule", "FaultPlan", "FaultyCommManager",
    "LoadPhase", "DiurnalTrace", "TraceLoadGen", "TraceShapedCommManager",
    "PaceBounds", "PaceController", "PaceDecision", "add_steering_args",
    "RetryPolicy", "RoundPolicy", "RoundController", "PeerUnreachableError",
    "send_with_retry", "aggregate_reports", "fold_entries_fp64",
    "ROUND_COMPLETE", "ROUND_DEGRADED", "ROUND_ABANDONED",
    "RoundRecovery",
    "SimResilience", "ResilientFedAvgClient", "ResilientFedAvgServer",
    "add_resilience_args", "quadratic_trainer", "run_tcp_fedavg",
    "AsyncAggPolicy", "BufferedAggregator", "AsyncBufferedFedAvgServer",
    "staleness_weight", "add_async_args", "run_async_tcp_fedavg",
]
