"""Deterministic fault injection for the control-plane transports.

Production FL is defined by churn (Bonawitz et al., MLSys 2019 §3: devices
drop out of every round), but a test suite cannot wait for real networks to
misbehave. This module makes every failure mode a *scheduled, seeded event*:
a :class:`FaultPlan` compiles a set of :class:`FaultRule`\\ s into per-rank
action streams, and :class:`FaultyCommManager` wraps any
``BaseCommunicationManager`` (local, tcp, mqtt) to apply them at send time.
Two runs with the same plan and the same per-rank send sequences take
byte-identical fault decisions -- the property ``tests/test_resilience.py``
pins and the chaos smoke in ``scripts/ci.sh`` relies on.

Faults are injected on the *send* side only: each rank's outbound sequence
is totally ordered (one sender thread), so per-rank decisions are
reproducible even though cross-rank interleaving is not. Supported actions:

- ``drop``      -- the message never reaches the wire.
- ``delay``     -- the send happens ``delay_s`` late (straggler).
- ``stall``     -- like ``delay``, but the intent is "past the server's
                   report deadline"; kept distinct so schedules read as the
                   failure they model.
- ``duplicate`` -- the frame is sent twice (at-least-once transports).
- ``reorder``   -- the message is held back and sent after the *next*
                   outbound message (pending holds flush on stop/kill).
- ``kill``      -- the rank dies: every later send/receive is swallowed and
                   the transport is severed abruptly (no GOODBYE), so the
                   server observes ``MSG_TYPE_PEER_LOST``, exactly like a
                   powered-off client.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from fedml_tpu.core.locks import audited_lock
from fedml_tpu.core.comm.base import (BaseCommunicationManager,
                                      MSG_TYPE_PEER_LOST)
from fedml_tpu.core.message import Message

ACTIONS = ("drop", "delay", "stall", "duplicate", "reorder", "kill")


@dataclass(frozen=True)
class FaultRule:
    """One scheduled (or probabilistic) fault.

    Matching is per sending rank over that rank's outbound messages:

      rank:     sending rank the rule applies to (None = every rank).
      msg_type: only messages of this type count as matches (None = all;
                transport-internal frames never match).
      nth:      fire on the nth matching message, 1-based (exact,
                deterministic). Mutually exclusive with ``p``.
      p:        fire with probability ``p`` per matching message, drawn
                from the plan's per-rank seeded stream -- still
                reproducible given the same seed and send sequence.
      action:   one of :data:`ACTIONS`.
      delay_s:  sleep for delay/stall actions.
    """

    action: str
    rank: Optional[int] = None
    msg_type: Optional[str] = None
    nth: Optional[int] = None
    p: Optional[float] = None
    delay_s: float = 0.0

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}; "
                             f"expected one of {ACTIONS}")
        if (self.nth is None) == (self.p is None):
            raise ValueError(
                "exactly one of nth= (deterministic) or p= (seeded "
                f"probabilistic) must be set, got nth={self.nth} p={self.p}")
        if self.nth is not None and self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")


class FaultPlan:
    """A seed plus a rule set; ``for_rank(r)`` derives that rank's injector
    state (independent RNG stream + fresh match counters), so every rank's
    decisions are a pure function of ``(seed, rank, its send sequence)``."""

    def __init__(self, seed: int = 0, rules: tuple = ()):
        self.seed = int(seed)
        self.rules = tuple(rules)

    def for_rank(self, rank: int) -> "_RankFaults":
        rules = tuple(r for r in self.rules
                      if r.rank is None or r.rank == int(rank))
        return _RankFaults(self.seed, int(rank), rules)

    def wrap(self, comm: BaseCommunicationManager,
             rank: int) -> "FaultyCommManager":
        return FaultyCommManager(comm, self.for_rank(rank))


class _RankFaults:
    """Per-rank decision stream. Not thread-safe by design: one sender."""

    def __init__(self, seed, rank, rules):
        self.rank = rank
        self.rules = rules
        # independent, collision-free per-rank stream (SeedSequence spawn
        # keys, not ad-hoc seed arithmetic)
        self._rng = np.random.default_rng(
            np.random.SeedSequence(seed).spawn(rank + 1)[-1])
        self._matches = [0] * len(rules)
        self.decisions = []  # (send_index, action) audit log

    def decide(self, send_index: int, msg_type: str) -> list:
        """Actions firing for this outbound message (schedule order)."""
        fired = []
        for i, rule in enumerate(self.rules):
            if rule.msg_type is not None and rule.msg_type != msg_type:
                continue
            self._matches[i] += 1
            if rule.nth is not None:
                hit = self._matches[i] == rule.nth
            else:
                hit = bool(self._rng.random() < rule.p)
            if hit:
                fired.append(rule)
                self.decisions.append((send_index, rule.action))
        return fired


class FaultyCommManager(BaseCommunicationManager):
    """Transparent fault-injecting wrapper around any comm manager.

    Observer registration and the receive loop pass straight through to the
    inner manager, so FSMs are oblivious; only ``send_message`` consults the
    schedule. ``kill()`` (also reachable via a ``kill`` rule) severs the
    inner transport without a clean shutdown and swallows all later
    traffic in both directions.
    """

    def __init__(self, inner: BaseCommunicationManager, faults: _RankFaults,
                 sleep=time.sleep):
        self.inner = inner
        self.faults = faults
        self._sleep = sleep
        self._send_index = 0
        self._held = None  # reorder buffer (at most one message)
        self._dead = False
        self._lock = audited_lock()  # kill() may race the sender thread

    # -- fault application -------------------------------------------------
    def send_message(self, msg: Message, **kw):
        with self._lock:
            if self._dead:
                return
            idx = self._send_index
            self._send_index += 1
            fired = self.faults.decide(idx, msg.get_type())
        actions = [r.action for r in fired]
        if "kill" in actions:
            self.kill()
            return
        if "drop" in actions:
            logging.info("faults: rank %d dropping send #%d (type=%s)",
                         self.faults.rank, idx, msg.get_type())
            self._flush_held(**kw)
            return
        for r in fired:
            if r.action in ("delay", "stall"):
                logging.info("faults: rank %d %sing send #%d by %.3fs",
                             self.faults.rank, r.action, idx, r.delay_s)
                self._sleep(r.delay_s)
        if "reorder" in actions:
            with self._lock:
                if self._held is None:
                    self._held = (msg, kw)
                    return
        self.inner.send_message(msg, **kw)
        if "duplicate" in actions:
            self.inner.send_message(msg, **kw)
        self._flush_held(**kw)

    def _flush_held(self, **kw):
        with self._lock:
            held, self._held = self._held, None
        if held is not None and not self._dead:
            msg, held_kw = held
            self.inner.send_message(msg, **(held_kw or kw))

    def kill(self):
        """Die abruptly: no GOODBYE, no STOP -- peers observe a crash."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._held = None
        logging.info("faults: rank %d killed", self.faults.rank)
        sever = getattr(self.inner, "abort", None)
        if sever is not None:
            sever()
        else:  # transports without an abrupt-death hook: best-effort close
            close = getattr(self.inner, "close", None)
            if close is not None:
                close()

    # -- pass-through ------------------------------------------------------
    def add_observer(self, observer):
        # interpose: a dead rank must not deliver inbound messages either
        self.inner.add_observer(_DeadFilter(self, observer))

    def remove_observer(self, observer):
        # remove the matching interposer (identity on the wrapped observer)
        for obs in list(getattr(self.inner, "_observers", [])):
            if isinstance(obs, _DeadFilter) and obs.wrapped is observer:
                self.inner.remove_observer(obs)
                return
        self.inner.remove_observer(observer)

    def handle_receive_message(self):
        self.inner.handle_receive_message()

    def stop_receive_message(self):
        self._flush_held()
        self.inner.stop_receive_message()

    def __getattr__(self, name):
        # byte counters, close(), transport extras: delegate untouched
        return getattr(self.inner, name)


class _DeadFilter:
    """Observer interposer: drops deliveries after the wrapper died (a
    crashed process cannot handle the messages already in its mailbox).
    ``MSG_TYPE_PEER_LOST`` still passes -- it is synthesized locally by the
    transport, not received, and tests assert on it."""

    def __init__(self, manager: FaultyCommManager, wrapped):
        self.manager = manager
        self.wrapped = wrapped

    def receive_message(self, msg_type, msg_params):
        if self.manager._dead and str(msg_type) != MSG_TYPE_PEER_LOST:
            return
        self.wrapped.receive_message(msg_type, msg_params)


__all__ = ["ACTIONS", "FaultRule", "FaultPlan", "FaultyCommManager"]
