"""Deterministic fault injection for the control-plane transports.

Production FL is defined by churn (Bonawitz et al., MLSys 2019 §3: devices
drop out of every round), but a test suite cannot wait for real networks to
misbehave. This module makes every failure mode a *scheduled, seeded event*:
a :class:`FaultPlan` compiles a set of :class:`FaultRule`\\ s into per-rank
action streams, and :class:`FaultyCommManager` wraps any
``BaseCommunicationManager`` (local, tcp, mqtt) to apply them at send time.
Two runs with the same plan and the same per-rank send sequences take
byte-identical fault decisions -- the property ``tests/test_resilience.py``
pins and the chaos smoke in ``scripts/ci.sh`` relies on.

Faults are injected on the *send* side only: each rank's outbound sequence
is totally ordered (one sender thread), so per-rank decisions are
reproducible even though cross-rank interleaving is not. Supported actions:

- ``drop``      -- the message never reaches the wire.
- ``delay``     -- the send happens ``delay_s`` late (straggler).
- ``stall``     -- like ``delay``, but the intent is "past the server's
                   report deadline"; kept distinct so schedules read as the
                   failure they model.
- ``duplicate`` -- the frame is sent twice (at-least-once transports).
- ``reorder``   -- the message is held back and sent after the *next*
                   outbound message (pending holds flush on stop/kill).
- ``kill``      -- the rank dies: every later send/receive is swallowed and
                   the transport is severed abruptly (no GOODBYE), so the
                   server observes ``MSG_TYPE_PEER_LOST``, exactly like a
                   powered-off client.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from fedml_tpu.core.locks import audited_lock
from fedml_tpu.core.comm.base import (BaseCommunicationManager,
                                      MSG_TYPE_PEER_LOST)
from fedml_tpu.core.message import Message

ACTIONS = ("drop", "delay", "stall", "duplicate", "reorder", "kill")


@dataclass(frozen=True)
class FaultRule:
    """One scheduled (or probabilistic) fault.

    Matching is per sending rank over that rank's outbound messages:

      rank:     sending rank the rule applies to (None = every rank).
      msg_type: only messages of this type count as matches (None = all;
                transport-internal frames never match).
      nth:      fire on the nth matching message, 1-based (exact,
                deterministic). Mutually exclusive with ``p``.
      p:        fire with probability ``p`` per matching message, drawn
                from the plan's per-rank seeded stream -- still
                reproducible given the same seed and send sequence.
      action:   one of :data:`ACTIONS`.
      delay_s:  sleep for delay/stall actions.
    """

    action: str
    rank: Optional[int] = None
    msg_type: Optional[str] = None
    nth: Optional[int] = None
    p: Optional[float] = None
    delay_s: float = 0.0

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}; "
                             f"expected one of {ACTIONS}")
        if (self.nth is None) == (self.p is None):
            raise ValueError(
                "exactly one of nth= (deterministic) or p= (seeded "
                f"probabilistic) must be set, got nth={self.nth} p={self.p}")
        if self.nth is not None and self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")


class FaultPlan:
    """A seed plus a rule set; ``for_rank(r)`` derives that rank's injector
    state (independent RNG stream + fresh match counters), so every rank's
    decisions are a pure function of ``(seed, rank, its send sequence)``."""

    def __init__(self, seed: int = 0, rules: tuple = ()):
        self.seed = int(seed)
        self.rules = tuple(rules)

    def for_rank(self, rank: int) -> "_RankFaults":
        rules = tuple(r for r in self.rules
                      if r.rank is None or r.rank == int(rank))
        return _RankFaults(self.seed, int(rank), rules)

    def wrap(self, comm: BaseCommunicationManager,
             rank: int) -> "FaultyCommManager":
        return FaultyCommManager(comm, self.for_rank(rank))


class _RankFaults:
    """Per-rank decision stream. Not thread-safe by design: one sender."""

    def __init__(self, seed, rank, rules):
        self.rank = rank
        self.rules = rules
        # independent, collision-free per-rank stream (SeedSequence spawn
        # keys, not ad-hoc seed arithmetic)
        self._rng = np.random.default_rng(
            np.random.SeedSequence(seed).spawn(rank + 1)[-1])
        self._matches = [0] * len(rules)
        self.decisions = []  # (send_index, action) audit log

    def decide(self, send_index: int, msg_type: str) -> list:
        """Actions firing for this outbound message (schedule order)."""
        fired = []
        for i, rule in enumerate(self.rules):
            if rule.msg_type is not None and rule.msg_type != msg_type:
                continue
            self._matches[i] += 1
            if rule.nth is not None:
                hit = self._matches[i] == rule.nth
            else:
                hit = bool(self._rng.random() < rule.p)
            if hit:
                fired.append(rule)
                self.decisions.append((send_index, rule.action))
        return fired


class FaultyCommManager(BaseCommunicationManager):
    """Transparent fault-injecting wrapper around any comm manager.

    Observer registration and the receive loop pass straight through to the
    inner manager, so FSMs are oblivious; only ``send_message`` consults the
    schedule. ``kill()`` (also reachable via a ``kill`` rule) severs the
    inner transport without a clean shutdown and swallows all later
    traffic in both directions.
    """

    def __init__(self, inner: BaseCommunicationManager, faults: _RankFaults,
                 sleep=time.sleep):
        self.inner = inner
        self.faults = faults
        self._sleep = sleep
        self._send_index = 0
        self._held = None  # reorder buffer (at most one message)
        self._dead = False
        self._lock = audited_lock()  # kill() may race the sender thread

    # -- fault application -------------------------------------------------
    def send_message(self, msg: Message, **kw):
        with self._lock:
            if self._dead:
                return
            idx = self._send_index
            self._send_index += 1
            fired = self.faults.decide(idx, msg.get_type())
        actions = [r.action for r in fired]
        if "kill" in actions:
            self.kill()
            return
        if "drop" in actions:
            logging.info("faults: rank %d dropping send #%d (type=%s)",
                         self.faults.rank, idx, msg.get_type())
            self._flush_held(**kw)
            return
        for r in fired:
            if r.action in ("delay", "stall"):
                logging.info("faults: rank %d %sing send #%d by %.3fs",
                             self.faults.rank, r.action, idx, r.delay_s)
                self._sleep(r.delay_s)
        if "reorder" in actions:
            with self._lock:
                if self._held is None:
                    self._held = (msg, kw)
                    return
        self.inner.send_message(msg, **kw)
        if "duplicate" in actions:
            self.inner.send_message(msg, **kw)
        self._flush_held(**kw)

    def _flush_held(self, **kw):
        with self._lock:
            held, self._held = self._held, None
        if held is not None and not self._dead:
            msg, held_kw = held
            self.inner.send_message(msg, **(held_kw or kw))

    def kill(self):
        """Die abruptly: no GOODBYE, no STOP -- peers observe a crash."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._held = None
        logging.info("faults: rank %d killed", self.faults.rank)
        sever = getattr(self.inner, "abort", None)
        if sever is not None:
            sever()
        else:  # transports without an abrupt-death hook: best-effort close
            close = getattr(self.inner, "close", None)
            if close is not None:
                close()

    # -- pass-through ------------------------------------------------------
    def add_observer(self, observer):
        # interpose: a dead rank must not deliver inbound messages either
        self.inner.add_observer(_DeadFilter(self, observer))

    def remove_observer(self, observer):
        # remove the matching interposer (identity on the wrapped observer)
        for obs in list(getattr(self.inner, "_observers", [])):
            if isinstance(obs, _DeadFilter) and obs.wrapped is observer:
                self.inner.remove_observer(obs)
                return
        self.inner.remove_observer(observer)

    def handle_receive_message(self):
        self.inner.handle_receive_message()

    def stop_receive_message(self):
        self._flush_held()
        self.inner.stop_receive_message()

    def __getattr__(self, name):
        # byte counters, close(), transport extras: delegate untouched
        return getattr(self.inner, name)


# -- diurnal trace-driven load generation ------------------------------------
#
# The fault rules above model *point* failures; a production fleet's
# dominant signal is the *load curve* -- day/night arrival-rate swings,
# correlated dropouts (a region goes dark for hours, not per-message),
# latency outages, flash crowds (Bonawitz MLSys'19 S3). The classes
# below make that curve a seeded, replayable schedule: a
# :class:`DiurnalTrace` is a JSON-serializable list of phases, a
# :class:`TraceLoadGen` derives deterministic per-(rank, event)
# delay/dropout decisions from it, and :class:`TraceShapedCommManager`
# applies them to any transport at send time (same ``wrap(comm, rank)``
# surface as :class:`FaultPlan`, so ``run_tcp_fedavg``/
# ``run_async_tcp_fedavg`` consume a trace through their existing
# ``fault_plan=`` parameter). ``net/soak.py``'s swarm replays the same
# JSON format (``--trace``), and :meth:`TraceLoadGen.sim_miss_fn` plugs
# the dropout curve into ``SimResilience`` for the wall-clock-free
# simulation rounds. Pace steering (resilience/steering.py) is proven
# against these traces.


@dataclass(frozen=True)
class LoadPhase:
    """One phase of a diurnal load curve.

    Args:
      dur_s: phase duration (trace-relative wall seconds).
      delay_s: mean client reply delay during the phase (the arrival
        curve: small = flash crowd / healthy day, large = outage).
      jitter: uniform multiplicative delay jitter -- an individual reply
        sleeps ``delay_s * (1 + jitter * U[-1, 1))``.
      dropout_p: fraction of ranks *dark* for this phase occurrence.
        Correlated by construction: a rank is dark (drops every shaped
        message) for the whole occurrence, decided once from
        ``(seed, cycle, phase_index, rank)`` -- the region-outage shape,
        not per-message coin flips.
      name: label for records/logs ("day", "night", "outage", ...).
    """

    dur_s: float
    delay_s: float = 0.0
    jitter: float = 0.5
    dropout_p: float = 0.0
    name: str = ""

    def __post_init__(self):
        if self.dur_s <= 0:
            raise ValueError("LoadPhase.dur_s must be > 0")
        if not 0.0 <= self.dropout_p <= 1.0:
            raise ValueError("LoadPhase.dropout_p must be in [0, 1]")


class DiurnalTrace:
    """A seeded, repeating (or one-shot) sequence of load phases,
    JSON-round-trippable so a measured curve replays bit-identically
    across runs, hosts, and the soak swarm subprocess."""

    def __init__(self, phases, repeat=True, seed=0):
        self.phases = tuple(phases)
        if not self.phases:
            raise ValueError("DiurnalTrace needs at least one phase")
        self.repeat = bool(repeat)
        self.seed = int(seed)
        self.total_s = float(sum(p.dur_s for p in self.phases))

    def locate(self, t):
        """Phase active at trace-relative time ``t``: returns
        ``(cycle, phase_index, phase)``. Past the end of a one-shot
        trace the last phase holds."""
        t = max(0.0, float(t))
        if self.repeat:
            cycle, t = divmod(t, self.total_s)
            cycle = int(cycle)
        else:
            cycle = 0
            t = min(t, self.total_s - 1e-9)
        acc = 0.0
        for i, p in enumerate(self.phases):
            acc += p.dur_s
            if t < acc:
                return cycle, i, p
        return cycle, len(self.phases) - 1, self.phases[-1]

    # -- JSON replay format --------------------------------------------------
    def to_dict(self) -> dict:
        return {"seed": self.seed, "repeat": self.repeat,
                "phases": [{"dur_s": p.dur_s, "delay_s": p.delay_s,
                            "jitter": p.jitter, "dropout_p": p.dropout_p,
                            "name": p.name} for p in self.phases]}

    @classmethod
    def from_dict(cls, d) -> "DiurnalTrace":
        return cls([LoadPhase(**p) for p in d["phases"]],
                   repeat=bool(d.get("repeat", True)),
                   seed=int(d.get("seed", 0)))

    def to_file(self, path):
        import json
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path

    @classmethod
    def from_file(cls, path) -> "DiurnalTrace":
        import json
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @classmethod
    def example(cls, scale=1.0, dropout=0.5, seed=0) -> "DiurnalTrace":
        """The canonical day/outage/night/flash curve the steering bench
        and the ci soak smoke replay (scaled; see docs/RESILIENCE.md
        "Pace steering"). The outage leads so a fixed short deadline
        meets it before finishing the run; the night's correlated
        dropouts make the cohort target unreachable, so every fixed
        config pays its full deadline per night round."""
        s = float(scale)
        return cls([
            LoadPhase(dur_s=0.4 * s, delay_s=0.05, jitter=0.5,
                      name="day"),
            LoadPhase(dur_s=6.0 * s, delay_s=1.5, jitter=0.2,
                      name="outage"),
            LoadPhase(dur_s=15.0 * s, delay_s=0.3, jitter=0.5,
                      dropout_p=dropout, name="night"),
            LoadPhase(dur_s=0.4 * s, delay_s=0.02, jitter=0.5,
                      name="flash"),
        ], repeat=True, seed=seed)


class TraceLoadGen:
    """Deterministic decision stream over a :class:`DiurnalTrace`.

    Every decision is a pure function of ``(seed, keys)`` -- dark ranks
    are keyed ``(seed, cycle, phase_index, rank)`` (correlated for the
    whole phase occurrence), reply delays ``(seed, rank, event_index)``
    (reproducible given the same per-rank send sequence, exactly the
    :class:`FaultPlan` contract). ``wrap(comm, rank)`` matches
    ``FaultPlan.wrap`` so the run drivers take a trace through their
    ``fault_plan=`` parameter unchanged.
    """

    def __init__(self, trace: DiurnalTrace, seed=None,
                 msg_type: str = "res_report", clock=time.monotonic,
                 population=None):
        self.trace = trace
        self.seed = trace.seed if seed is None else int(seed)
        self.msg_type = msg_type
        self._clock = clock
        # LAZY epoch: trace time 0 is the FIRST shaped event, not
        # generator construction -- transport handshakes (hundreds of
        # ms at tens of ranks) must not eat the first phase, or two
        # configs compared "on the same trace" see different curves
        self._epoch = None
        # known population => dark sets are exact-count (a seeded
        # permutation's first round(p*n) ranks), not per-rank Bernoulli:
        # "half the fleet is dark" then means exactly half, which is
        # both the correlated-outage shape and what keeps quorum math
        # deterministic in the steering bench/tests
        self.population = (tuple(sorted(int(r) for r in population))
                           if population is not None else None)

    def reset_epoch(self):
        """Re-arm the lazy epoch (t=0 becomes the next shaped event)."""
        self._epoch = None

    def trace_time(self):
        if self._epoch is None:
            self._epoch = self._clock()
        return self._clock() - self._epoch

    def dark(self, cycle, phase_index, rank, p) -> bool:
        if p <= 0:
            return False
        if p >= 1:
            return True
        if self.population is not None:
            k = int(round(p * len(self.population)))
            if k <= 0:
                return False
            perm = np.random.default_rng(
                (self.seed, int(cycle), int(phase_index))).permutation(
                    len(self.population))
            return int(rank) in {self.population[i] for i in perm[:k]}
        rng = np.random.default_rng(
            (self.seed, int(cycle), int(phase_index), int(rank)))
        return bool(rng.random() < p)

    def reply_delay(self, rank, event_index, phase: LoadPhase) -> float:
        if phase.delay_s <= 0:
            return 0.0
        u = np.random.default_rng(
            (self.seed, 7, int(rank), int(event_index))).random()
        return float(phase.delay_s * (1.0 + phase.jitter * (2.0 * u - 1.0)))

    def decide(self, rank, event_index, t):
        """``("drop", phase)`` or ``("delay", seconds, phase)`` for one
        shaped message at trace time ``t``."""
        cycle, idx, phase = self.trace.locate(t)
        if self.dark(cycle, idx, rank, phase.dropout_p):
            return ("drop", phase)
        return ("delay", self.reply_delay(rank, event_index, phase), phase)

    def wrap(self, comm: BaseCommunicationManager,
             rank: int) -> "TraceShapedCommManager":
        return TraceShapedCommManager(comm, self, rank)

    def sim_miss_fn(self, round_s=1.0):
        """Deadline-miss oracle for ``SimResilience(miss_fn=...)``: the
        simulation rounds have no wall clock, so round ``r`` maps to
        virtual trace time ``r * round_s`` and a client misses when its
        phase marks it dark. Pure function of (seed, round, client) --
        the bitwise-reproducible half of the steering determinism
        gate."""

        def miss(round_idx, attempt, client_id):
            del attempt  # an abandoned re-run re-samples, same phase
            cycle, idx, phase = self.trace.locate(
                float(round_idx) * float(round_s))
            return self.dark(cycle, idx, client_id, phase.dropout_p)

        return miss


class TraceShapedCommManager(BaseCommunicationManager):
    """Send-side trace shaper: only ``gen.msg_type`` messages (client
    reports, by default) are delayed/dropped -- control traffic (HELLO,
    syncs, GOODBYE) flows clean, exactly like a slow-uplink device whose
    downlink still works.

    Unlike :class:`FaultyCommManager`'s ``delay`` action (which stalls
    the *sender thread*, modelling a busy device), the trace delay is
    delivered by a timer -- it models network/uplink LATENCY: the
    client's handler thread is immediately free for the next sync, so
    consecutive round attempts see independent delays instead of one
    slow device serializing them (which would cascade abandons under a
    deadline prober). The decision stream stays on the sender thread
    (one sender per rank, the :class:`_RankFaults` contract); only the
    delivery hops threads."""

    def __init__(self, inner: BaseCommunicationManager, gen: TraceLoadGen,
                 rank: int, timer_factory=threading.Timer):
        self.inner = inner
        self.gen = gen
        self.rank = int(rank)
        self._timer_factory = timer_factory
        self._events = 0
        self.dropped = 0
        self.delayed_s = 0.0

    def send_message(self, msg: Message, **kw):
        if msg.get_type() != self.gen.msg_type:
            self.inner.send_message(msg, **kw)
            return
        idx = self._events
        self._events += 1
        action = self.gen.decide(self.rank, idx, self.gen.trace_time())
        if action[0] == "drop":
            self.dropped += 1
            logging.info("trace: rank %d dark in phase %r -- dropping "
                         "send #%d", self.rank, action[1].name, idx)
            return
        _, delay, _phase = action
        if delay <= 0:
            self.inner.send_message(msg, **kw)
            return
        self.delayed_s += delay
        t = self._timer_factory(delay, self._deliver, args=(msg, kw))
        t.daemon = True
        t.start()

    def _deliver(self, msg, kw):
        try:
            self.inner.send_message(msg, **kw)
        except (ConnectionError, OSError, KeyError):
            # the run ended (or the peer died) while this reply was in
            # flight: a real network would drop it on the floor too
            logging.debug("trace: rank %d delayed send arrived after "
                          "teardown", self.rank)

    # -- pass-through ------------------------------------------------------
    def add_observer(self, observer):
        self.inner.add_observer(observer)

    def remove_observer(self, observer):
        self.inner.remove_observer(observer)

    def handle_receive_message(self):
        self.inner.handle_receive_message()

    def stop_receive_message(self):
        self.inner.stop_receive_message()

    def __getattr__(self, name):
        return getattr(self.inner, name)


class _DeadFilter:
    """Observer interposer: drops deliveries after the wrapper died (a
    crashed process cannot handle the messages already in its mailbox).
    ``MSG_TYPE_PEER_LOST`` still passes -- it is synthesized locally by the
    transport, not received, and tests assert on it."""

    def __init__(self, manager: FaultyCommManager, wrapped):
        self.manager = manager
        self.wrapped = wrapped

    def receive_message(self, msg_type, msg_params):
        if self.manager._dead and str(msg_type) != MSG_TYPE_PEER_LOST:
            return
        self.wrapped.receive_message(msg_type, msg_params)


__all__ = ["ACTIONS", "FaultRule", "FaultPlan", "FaultyCommManager",
           "LoadPhase", "DiurnalTrace", "TraceLoadGen",
           "TraceShapedCommManager"]
