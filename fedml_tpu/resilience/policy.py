"""Resilience policies: send retry/backoff, over-selection, deadlines.

The reference's server protocol blocks forever on the slowest client
(``FedAVGAggregator.py:50-56``); Bonawitz et al. (MLSys 2019, §3) replace
that with the pace-steering triple this module implements:

- **over-selection**: select ``ceil((1+eps) * C)`` clients, aggregate the
  first ``C`` reports (:meth:`RoundPolicy.select_count`);
- **report deadline**: when the timer fires with at least ``quorum * C``
  reports the round completes *degraded* over the reporting subset;
- **abandonment**: below quorum the round is abandoned and re-run with a
  fresh cohort (:class:`RoundController` raises the ``abandoned`` outcome;
  the integration layer re-samples with an incremented attempt counter).

Plus the transport-side half: :func:`send_with_retry` wraps control-plane
sends in bounded exponential backoff and, once the cap is exhausted,
dispatches ``MSG_TYPE_PEER_LOST`` to the manager's observers -- a peer we
cannot reach after retries is indistinguishable from a dead one, and the
FSM's existing peer-lost path (re-cohort or fail-fast) takes over.

Aggregation over the reporting subset renormalizes by construction:
:func:`aggregate_reports` divides by the *reporting* clients' sample total,
never the selected cohort's, so a dropped client shifts weight to its
surviving peers instead of biasing the average toward zero.

The policy/fold primitives themselves now live in
:mod:`fedml_tpu.program` (the one ``RoundProgram`` subsystem behind both
paradigms): ``RoundPolicy`` is the program's
:class:`~fedml_tpu.program.cohort.CohortPolicy` and
``fold_entries_fp64`` / ``aggregate_reports`` are the program's
aggregation leg, re-exported here under their historical names. This
module keeps what is genuinely control-plane: the retry/backoff layer
and the deadline-driven :class:`RoundController`.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from fedml_tpu.core.comm.base import MSG_TYPE_PEER_LOST
from fedml_tpu.core.locks import audited_lock
from fedml_tpu.core.message import Message
from fedml_tpu.observability.flightrec import get_flight_recorder
from fedml_tpu.observability.registry import get_registry
from fedml_tpu.program.aggregation import (  # noqa: F401 (re-export)
    aggregate_reports, fold_entries_fp64)
from fedml_tpu.program.cohort import CohortPolicy as RoundPolicy


class PeerUnreachableError(ConnectionError):
    """Raised by :func:`send_with_retry` after the retry cap: the receiver
    is treated as lost (``MSG_TYPE_PEER_LOST`` has been dispatched)."""


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff for one control-plane send.

    ``delay(k)`` for attempt k (0-based) is ``base_delay * multiplier**k``
    capped at ``max_delay``; ``timeout_s`` bounds the whole message
    (attempts stop when the budget is spent even if retries remain)."""

    max_retries: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    timeout_s: float = 30.0

    def delay(self, attempt: int) -> float:
        return min(self.base_delay * self.multiplier ** attempt,
                   self.max_delay)


def send_with_retry(comm, msg: Message, policy: RetryPolicy,
                    counters=None, sleep=time.sleep,
                    clock=time.monotonic) -> int:
    """Send ``msg`` through ``comm`` with retry + exponential backoff.

    Returns the number of retries used (0 = first try worked). Retries
    count into ``counters["retries"]`` when a dict is passed. Resends are
    flagged to the transport (``is_resend=True``) so wire accounting stays
    honest: the resent frame's bytes hit ``bytes_on_wire`` again while the
    logical payload is counted once (see ``TcpCommManager.send_message``).

    On exhaustion (or a spent ``timeout_s`` budget) the receiver is
    declared lost: ``MSG_TYPE_PEER_LOST`` is dispatched to ``comm``'s
    observers (via the transport's own ``_notify_peer_lost`` when it has
    one, so dedup applies) and :class:`PeerUnreachableError` is raised.
    """
    deadline = clock() + policy.timeout_s
    attempt = 0
    while True:
        try:
            comm.send_message(msg, is_resend=attempt > 0)
            return attempt
        except (ConnectionError, OSError, KeyError) as e:
            # KeyError: the tcp hub unrouted the peer (died or never
            # joined) -- same disposition as a failed write
            last = e
        attempt += 1
        if attempt > policy.max_retries or clock() >= deadline:
            receiver = int(msg.get_receiver_id())
            logging.warning(
                "send_with_retry: giving up on rank %s after %d attempt(s) "
                "(%s); declaring peer lost", receiver, attempt, last)
            _dispatch_peer_lost(comm, receiver)
            raise PeerUnreachableError(
                f"rank {receiver} unreachable after {attempt} attempt(s): "
                f"{last}") from last
        if counters is not None:
            counters["retries"] = counters.get("retries", 0) + 1
        fr = get_flight_recorder()
        if fr is not None:
            fr.record("retry", dst=int(msg.get_receiver_id()),
                      type=msg.get_type(), attempt=attempt,
                      backoff_s=policy.delay(attempt - 1))
        reg = get_registry()
        if reg is not None:
            reg.inc("fed_send_retries_total",
                    help="control-plane send retries (backoff layer)")
        sleep(policy.delay(attempt - 1))


def _dispatch_peer_lost(comm, receiver):
    notify = getattr(comm, "_notify_peer_lost", None)
    if notify is not None:  # transport-native path dedups per peer
        notify(receiver)  # (tcp also flight-records + dumps there)
        return
    fr = get_flight_recorder()
    if fr is not None:
        fr.record("peer_lost", peer=receiver, transport="retry-layer")
        fr.dump("peer_lost", extra={"peer": receiver})
    lost = Message(MSG_TYPE_PEER_LOST, receiver, getattr(comm, "rank", 0))
    for obs in list(getattr(comm, "_observers", [])):
        obs.receive_message(MSG_TYPE_PEER_LOST, lost)


#: RoundController outcomes.
ROUND_COMPLETE = "complete"    # target reports arrived
ROUND_DEGRADED = "degraded"    # deadline hit with quorum <= reports < target
ROUND_ABANDONED = "abandoned"  # below quorum at the deadline (or cohort died)


class RoundController:
    """Deadline-based report collector for one round attempt at a time.

    Thread-safe: reports arrive on transport serve threads, the deadline
    fires on a timer thread, and peer-lost notifications can come from
    either. Exactly one of ``on_complete(reports, outcome)`` /
    ``on_abandoned(reports)`` fires per ``begin()``; late, duplicate and
    overflow reports are counted, not aggregated (over-selection's surplus
    reports land in ``counters["overflow_reports"]`` by design).
    """

    def __init__(self, policy: RoundPolicy, on_complete, on_abandoned,
                 timer_factory=threading.Timer):
        self.policy = policy
        self._on_complete = on_complete
        self._on_abandoned = on_abandoned
        self._timer_factory = timer_factory
        self._lock = audited_lock()
        self._timer = None
        self._round = None
        self._attempt = None
        self._decided = True  # nothing in flight yet
        self.counters = {"late_reports": 0, "duplicate_reports": 0,
                         "overflow_reports": 0}

    def begin(self, round_idx: int, attempt: int, cohort, target: int):
        """Open collection for (round_idx, attempt) over ``cohort`` ranks;
        the round completes at ``target`` accepted reports."""
        with self._lock:
            if not self._decided:
                raise RuntimeError("previous round attempt still open")
            self._round, self._attempt = int(round_idx), int(attempt)
            self._cohort = set(int(r) for r in cohort)
            self._target = int(target)
            self._reports = {}
            self._lost = set()
            self._decided = False
            if self.policy.deadline_s > 0:
                # the timer carries its (round, attempt) generation:
                # cancel() cannot stop a callback already blocked on the
                # lock, and a stale timer must never decide the NEXT
                # attempt it happens to wake up inside
                self._timer = self._timer_factory(
                    self.policy.deadline_s, self._on_deadline,
                    args=(self._round, self._attempt))
                self._timer.daemon = True
                self._timer.start()

    def report(self, round_idx, attempt, rank, num_samples, payload) -> bool:
        """Returns True when the report was accepted into this attempt."""
        rank = int(rank)
        with self._lock:
            if (self._decided or int(round_idx) != self._round
                    or int(attempt) != self._attempt
                    or rank not in self._cohort):
                self.counters["late_reports"] += 1
                return False
            if rank in self._reports:
                self.counters["duplicate_reports"] += 1
                return False
            if len(self._reports) >= self._target:
                # over-selection surplus: the first `target` reports win
                self.counters["overflow_reports"] += 1
                return False
            self._reports[rank] = (float(num_samples), payload)
            done = len(self._reports) >= self._target
            if done:
                decision = self._decide_locked(ROUND_COMPLETE)
        if done:
            self._fire(decision)
        return True

    def admit(self, round_idx, attempt, rank) -> bool:
        """Mid-round cohort admission: add a rejoined rank to the OPEN
        (round_idx, attempt) so its report is accepted into *this*
        attempt instead of idling to the next round. The target is
        unchanged -- the resumed rank fills in for a lost or straggling
        cohort member rather than extending the round -- and a rank
        counted lost is un-lost (its fresh report is the recovery the
        resume exists for). Returns True when the rank was admitted;
        False when nothing is open, the generation moved on, or the
        rank is already in the cohort."""
        rank = int(rank)
        with self._lock:
            if (self._decided or int(round_idx) != self._round
                    or int(attempt) != self._attempt
                    or rank in self._cohort):
                return False
            self._cohort.add(rank)
            self._lost.discard(rank)
            return True

    def peer_lost(self, rank) -> None:
        """A cohort member died mid-round. When everyone still outstanding
        is dead the attempt resolves immediately instead of burning the
        rest of the deadline."""
        with self._lock:
            if self._decided:
                return
            self._lost.add(int(rank))
            outstanding = self._cohort - set(self._reports) - self._lost
            if outstanding or len(self._reports) >= self._target:
                return  # timer (or the target report) will decide
            decision = self._decide_locked(
                ROUND_DEGRADED if self._quorum_met_locked()
                else ROUND_ABANDONED)
        self._fire(decision)

    def _on_deadline(self, round_idx, attempt):
        with self._lock:
            if (self._decided or round_idx != self._round
                    or attempt != self._attempt):
                return  # stale generation: a newer attempt owns the round
            decision = self._decide_locked(
                ROUND_DEGRADED if self._quorum_met_locked()
                else ROUND_ABANDONED)
        self._fire(decision)

    def _quorum_met_locked(self):
        return len(self._reports) >= self.policy.quorum_count(self._target)

    def _decide_locked(self, outcome):
        self._decided = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        # the decision tuple carries its own generation: _fire runs
        # OUTSIDE the lock (turnover callbacks may re-enter begin), so by
        # the time it logs, self._round may already belong to the NEXT
        # attempt -- reading it there is a data race (fedcheck FL123)
        return (outcome, dict(self._reports), self._round, self._attempt,
                self._target)

    def _fire(self, decision):
        outcome, reports, round_idx, attempt, target = decision
        logging.info("round %s attempt %s: %s with %d/%d reports",
                     round_idx, attempt, outcome, len(reports), target)
        fr = get_flight_recorder()
        if fr is not None:
            fr.record("round_decision", outcome=outcome, round=round_idx,
                      attempt=attempt, reports=len(reports), target=target)
            if outcome == ROUND_ABANDONED:
                fr.dump("abandoned_round",
                        extra={"round": round_idx, "attempt": attempt,
                               "reports": len(reports), "target": target})
        reg = get_registry()
        if reg is not None:
            reg.inc("fed_round_attempts_total",
                    help="round-attempt decisions by outcome",
                    outcome=outcome)
        if outcome == ROUND_ABANDONED:
            self._on_abandoned(reports)
        else:
            self._on_complete(reports, outcome)

    def cancel(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._decided = True


__all__ = ["RetryPolicy", "RoundPolicy", "RoundController",
           "PeerUnreachableError", "send_with_retry", "aggregate_reports",
           "fold_entries_fp64",
           "ROUND_COMPLETE", "ROUND_DEGRADED", "ROUND_ABANDONED"]
