"""Flax model zoo: TPU-native re-designs of the reference's PyTorch models
(``fedml_api/model/``). All modules are NHWC (TPU-preferred layout) and return
logits; losses live in the TrainSpec layer so every model composes with every
FL algorithm.
"""

from fedml_tpu.models.linear import LogisticRegression  # noqa: F401
from fedml_tpu.models.cnn import CNNOriginalFedAvg, CNNDropOut  # noqa: F401
from fedml_tpu.models.resnet import CifarResNet, resnet56, resnet110  # noqa: F401
from fedml_tpu.models.resnet_gn import ResNetGN, resnet18_gn, resnet34_gn, resnet50_gn  # noqa: F401
from fedml_tpu.models.mobilenet import MobileNet  # noqa: F401
from fedml_tpu.models.mobilenet_v3 import MobileNetV3  # noqa: F401
from fedml_tpu.models.efficientnet import EfficientNet, efficientnet  # noqa: F401
from fedml_tpu.models.vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow  # noqa: F401
from fedml_tpu.models.transformer import TransformerLM, transformer_nwp  # noqa: F401
from fedml_tpu.models.moe import MoEBlock, MoEMLP, MoETransformerLM  # noqa: F401
from fedml_tpu.models.gkt import (  # noqa: F401
    GKTClientResNet, GKTServerResNet, resnet5_56, resnet8_56, resnet56_server)
from fedml_tpu.models.linear import DenseModel, LocalModel  # noqa: F401
from fedml_tpu.models.darts import (  # noqa: F401
    DARTSNetwork, DARTSFixedNetwork, Genotype, DARTS_V1, derive_genotype)
from fedml_tpu.models.factory import create_model  # noqa: F401
