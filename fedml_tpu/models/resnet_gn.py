"""Torchvision-layout ResNets with a GroupNorm knob. Parity: reference
``fedml_api/model/cv/resnet_gn.py:183-235`` (resnet18..152 where ``group_norm``
= channels-per-group; 0 selects BatchNorm -- ``norm2d`` at ``resnet_gn.py:26-33``)
and ``group_normalization.py:56-104`` (GroupNorm2d). Used for fed_cifar100
(ResNet-18 + GN, baseline 44.7% -- BASELINE.md).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


def _norm(group_norm: int, train: bool, dtype):
    """``group_norm`` > 0: GroupNorm with that many channels per group
    (reference ``norm2d``); otherwise BatchNorm."""
    if group_norm > 0:
        def gn(name=None):
            # flax GroupNorm takes num_groups; convert channels-per-group at
            # call time via group_size
            return nn.GroupNorm(num_groups=None, group_size=group_norm,
                                epsilon=1e-5, dtype=dtype, name=name)
        return gn
    return partial(nn.BatchNorm, use_running_average=not train,
                   momentum=0.9, epsilon=1e-5, dtype=dtype)


class _BasicBlockGN(nn.Module):
    filters: int
    strides: int
    norm: Any
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=self.strides, padding=1,
                 name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding=1, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=self.strides,
                            name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class _BottleneckGN(nn.Module):
    filters: int
    strides: int
    norm: Any
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(self.norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=self.strides, padding=1,
                 name="conv2")(y)
        y = nn.relu(self.norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), strides=self.strides,
                            name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNetGN(nn.Module):
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    block: str = "basic"  # "basic" | "bottleneck"
    num_classes: int = 1000
    group_norm: int = 32  # channels per group; 0 = BatchNorm
    small_input: bool = True  # 3x3 stem for CIFAR-size inputs
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.group_norm, train, self.dtype)
        block_cls = _BasicBlockGN if self.block == "basic" else _BottleneckGN
        x = x.astype(self.dtype)
        if self.small_input:
            x = nn.Conv(64, (3, 3), padding=1, use_bias=False,
                        dtype=self.dtype, name="conv1")(x)
            x = nn.relu(norm(name="bn1")(x))
        else:
            x = nn.Conv(64, (7, 7), strides=2, padding=3, use_bias=False,
                        dtype=self.dtype, name="conv1")(x)
            x = nn.relu(norm(name="bn1")(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, size in enumerate(self.stage_sizes):
            filters = 64 * (2 ** stage)
            for b in range(size):
                strides = 2 if (stage > 0 and b == 0) else 1
                x = block_cls(filters, strides, norm, dtype=self.dtype,
                              name=f"layer{stage + 1}_block{b}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


def resnet18_gn(class_num=10, group_norm=32, **kw):
    return ResNetGN(stage_sizes=(2, 2, 2, 2), block="basic",
                    num_classes=class_num, group_norm=group_norm, **kw)


def resnet34_gn(class_num=10, group_norm=32, **kw):
    return ResNetGN(stage_sizes=(3, 4, 6, 3), block="basic",
                    num_classes=class_num, group_norm=group_norm, **kw)


def resnet50_gn(class_num=10, group_norm=32, **kw):
    return ResNetGN(stage_sizes=(3, 4, 6, 3), block="bottleneck",
                    num_classes=class_num, group_norm=group_norm, **kw)
