"""Lane-packed conv models: the MXU-shaped lowering of per-lane convs
(:data:`PACKED_FAMILIES`: the CIFAR ResNets and the FedAvg-paper CNN).

Why this exists (docs/PERFORMANCE.md, round-4 analysis): the packed-lane
engine (``parallel/engine.py`` LaneRunner) trains L independent per-lane
model replicas by ``jax.vmap`` over lane-stacked params. XLA lowers the
lane-batched convolutions as ``feature_group_count=L`` grouped convs with
per-group input channels equal to the MODEL's channel count -- 16/32/64
for ResNet-56/CIFAR -- against the MXU's K-granularity of 128, wasting
8x/4x/2x of every systolic pass (measured 8.9% MFU, ~25-30% shape
ceiling).

This module re-expresses the same L-replica computation with the lane
axis folded into channels *under our control*:

- activations live as ``[B, H, W, L*C]`` (lane-major channels);
- each conv merges ``g = 128 // C_in`` lanes per group into ONE grouped
  conv whose per-group K is ``g*C_in = 128`` (a full MXU tile), with the
  per-lane weights embedded block-diagonally inside each group. The
  extra multiply-adds against the off-diagonal zero blocks are FLOPs the
  MXU was already wasting on underfilled tiles in the grouped form --
  now they ride full tiles with no group loop;
- BatchNorm over merged channels IS per-lane BatchNorm (the reduction
  set per (lane, channel) is identical); the head is a per-lane einsum.

Numerics match ``jax.vmap(model.apply)`` over lane-stacked params to
float reassociation (oracle: ``tests/test_lane_packed.py``); autodiff
extracts per-lane weight grads through the block-diagonal embedding's
transpose (a gather of the diagonal blocks of the dense dW).

No reference analog: the reference trains one client per GPU process
(``FedAVGAggregator.py:58-87``) and never faces batched-weight lowering.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.cnn import CNNOriginalFedAvg
from fedml_tpu.models.resnet import CifarResNet

_BN_MOMENTUM = 0.9
_BN_EPS = 1e-5
#: MXU lane width: per-group input channels are padded up to this by
#: merging lanes (K granularity of the systolic array).
MXU_K = 128


def lane_merge(x):
    """``[L, B, H, W, C] -> [B, H, W, L*C]`` (lane-major channels)."""
    L, B, H, W, C = x.shape
    return jnp.transpose(x, (1, 2, 3, 0, 4)).reshape(B, H, W, L * C)


def lane_unmerge(x, L):
    """``[B, H, W, L*C] -> [L, B, H, W, C]``."""
    B, H, W, LC = x.shape
    return jnp.transpose(x.reshape(B, H, W, L, LC // L), (3, 0, 1, 2, 4))


def _lanes_per_group(L, ci, min_k=MXU_K):
    """Largest divisor of ``L`` with ``g*ci`` closest to (>= if possible)
    ``min_k``: how many lanes merge into one conv group."""
    g = max(1, min(L, min_k // max(ci, 1)))
    while L % g:
        g -= 1
    return g


#: PROVISIONAL per-conv strategy threshold for ``lowering="auto"``. The
#: corrected r5 shoot-out (``scripts/bench_lane_conv.py``, --inner 200,
#: docs/PERFORMANCE.md) only measured s1 (Ci=16) before the tunnel
#: wedged: bgc wins FORWARD-only there, and fwd+bwd is a tie (bgc
#: 0.259 ms vs blockdiag 0.244 ms). The Ci=32/64 crossover comes from
#: the floor-biased first run PERFORMANCE.md calls misleading; treat
#: this threshold as unverified until the s2/s3 rows land
#: (``scripts/tpu_watch_r5b.sh`` holds the next-window plan).
BGC_MAX_CI = 32


def merged_to_stacked(x, L):
    """``[B, H, W, L*C] -> [L*B, H, W, C]`` (batch-stacked lanes)."""
    B, H, W, LC = x.shape
    return lane_unmerge(x, L).reshape(L * B, H, W, LC // L)


def lane_conv_bgc(x, w, L, strides=(1, 1), padding=((1, 1), (1, 1))):
    """Per-lane conv via ``batch_group_count=L``: ZERO FLOP redundancy.

    ``x``: ``[L*B, H, W, Ci]`` batch-stacked (lane-major batch);
    ``w``: ``[L, kh, kw, Ci, Co]``. Returns **merged** ``[B, H', W',
    L*Co]`` -- XLA's batch-group conv writes feature group ``l`` from
    batch group ``l``, which IS the lane-major merged channel layout the
    rest of the packed pipeline (BN/relu/residual/head) runs on.
    """
    _, kh, kw, ci, co = w.shape
    rhs = jnp.transpose(w, (1, 2, 3, 0, 4)).reshape(kh, kw, ci, L * co)
    return jax.lax.conv_general_dilated(
        x, rhs, window_strides=strides, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"), batch_group_count=L)


def lane_conv(x, w, L, strides=(1, 1), padding=((1, 1), (1, 1)),
              min_k=MXU_K, strategy="blockdiag"):
    """Per-lane conv over merged activations.

    ``x``: ``[B, H, W, L*Ci]`` lane-major; ``w``: ``[L, kh, kw, Ci, Co]``
    per-lane HWIO kernels. Returns ``[B, H', W', L*Co]``.

    ``strategy="blockdiag"``: ``g`` lanes merge per group (``g*Ci ~
    128``); the group's weights are the g x g block-diagonal embedding
    of the lanes' kernels, so the grouped conv computes exactly the
    per-lane convs -- on full MXU K-tiles instead of ``Ci``-wide ones
    (g x redundant FLOPs riding otherwise-idle tiles).

    ``strategy="bgc"``: re-stack lanes into the batch (one transpose)
    and run the zero-redundancy ``batch_group_count`` conv
    (:func:`lane_conv_bgc`) -- measured faster at Ci<=32 where
    block-diag redundancy is 8x/4x (r5 shoot-out).

    ``strategy="pallas"``: the bgc forward (bitwise-identical program)
    with the backward dW -- the measured lane-penalty cost center --
    computed by the Pallas grouped-conv dW kernel
    (:mod:`fedml_tpu.ops.pallas_grouped_conv`); strided convs fall back
    to XLA's dW inside the custom vjp.
    """
    _, kh, kw, ci, co = w.shape
    if strategy == "pallas":
        from fedml_tpu.ops.pallas_grouped_conv import lane_conv_pallas
        return lane_conv_pallas(merged_to_stacked(x, L), w, L, strides,
                                padding)
    if strategy == "bgc":
        return lane_conv_bgc(merged_to_stacked(x, L), w, L,
                             strides=strides, padding=padding)
    g = _lanes_per_group(L, ci, min_k)
    G = L // g
    wg = w.reshape(G, g, kh, kw, ci, co)
    # wd[j, h, w, l*ci+i, m*co+o] = wg[j, m, h, w, i, o] * (l == m):
    # inputs of lane l contribute only to outputs of lane m == l. The
    # einsum has no contraction -- every output element is one product
    # with 1.0 or 0.0, so the embedding is exact in any dtype.
    eye = jnp.eye(g, dtype=w.dtype)
    wd = jnp.einsum("gmhwio,lm->ghwlimo", wg, eye)
    rhs = (wd.reshape(G, kh, kw, g * ci, g * co)
           .transpose(1, 2, 3, 0, 4)
           .reshape(kh, kw, g * ci, G * g * co))
    return jax.lax.conv_general_dilated(
        x, rhs, window_strides=strides, padding=padding,
        feature_group_count=G,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def lane_bn(x, p, ra, L, train, dtype):
    """Per-lane BatchNorm on merged activations; flax semantics
    (fp32 stats, fast variance, clip-negative, momentum 0.9, eps 1e-5).

    ``p``: ``{"scale","bias"} [L, C]``; ``ra``: ``{"mean","var"} [L, C]``
    running stats. Returns ``(y, new_ra)``.
    """
    scale = p["scale"].reshape(-1)  # [L*C], lane-major like x's channels
    bias = p["bias"].reshape(-1)
    if train:
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=(0, 1, 2))
        mu2 = jnp.mean(xf * xf, axis=(0, 1, 2))
        var = jnp.maximum(0.0, mu2 - mu * mu)
        new_ra = {
            "mean": _BN_MOMENTUM * ra["mean"]
            + (1 - _BN_MOMENTUM) * mu.reshape(ra["mean"].shape),
            "var": _BN_MOMENTUM * ra["var"]
            + (1 - _BN_MOMENTUM) * var.reshape(ra["var"].shape),
        }
    else:
        mu, var = ra["mean"].reshape(-1), ra["var"].reshape(-1)
        new_ra = ra
    # flax _normalize: y = (x - mean) * (rsqrt(var+eps) * scale) + bias
    # in fp32, then cast to the module dtype
    y = (x.astype(jnp.float32) - mu) * (
        jax.lax.rsqrt(var + _BN_EPS) * scale) + bias
    return y.astype(dtype), new_ra


def make_lane_packed_apply(model, L: int, lowering: str = "blockdiag"):
    """Build the packed apply for ``L`` lanes of a supported model.

    Returns ``apply_fn(stacked_vars, x, train) -> (logits, new_stats)``
    where ``stacked_vars`` is ``{"params"[, "batch_stats"]}`` with every
    leaf lane-stacked (leading ``L`` -- the exact layout the LaneRunner
    carries), ``x`` is ``[L, B, ...]``, ``logits`` ``[L, B, classes]``
    and ``new_stats`` is the lane-stacked batch_stats pytree (``{}`` for
    stat-free families).

    ``lowering`` selects the per-lane conv strategy (CifarResNet only):
    ``"blockdiag"`` everywhere, ``"bgc"`` everywhere, ``"pallas"``
    (bgc forward + the Pallas grouped-conv dW kernel on every stride-1
    conv -- the backward-dW cost-center candidate staged for the r8
    ``--lane_lowering`` A/B), or ``"auto"`` -- per conv by input channel
    count (:data:`BGC_MAX_CI`): the measured optimum is batch-group
    convs for the narrow stages (Ci<=32) and the block-diagonal
    embedding for the wide one (Ci=64).

    Supported families: :class:`CifarResNet` (the ResNet-56 flagship)
    and :class:`CNNOriginalFedAvg` (the FedAvg-paper FEMNIST CNN, whose
    1-channel stem underfills the MXU's K dim 128x in the vmap lowering
    -- the merge is worth the most there).
    """
    if isinstance(model, CNNOriginalFedAvg):
        return _make_cnn_apply(model, L)
    if not isinstance(model, CifarResNet):
        raise TypeError(
            f"lane-packed apply supports "
            f"{', '.join(c.__name__ for c in PACKED_FAMILIES)}, "
            f"got {type(model).__name__}")
    if lowering not in ("blockdiag", "bgc", "auto", "pallas"):
        raise ValueError(f"unknown lane lowering {lowering!r}")
    n = (model.depth - 2) // 6
    dtype = model.dtype

    def apply_fn(stacked_vars, x, train=False):
        p, bs = stacked_vars["params"], stacked_vars["batch_stats"]
        new_bs = {}
        x = lane_merge(x.astype(dtype))

        def conv(name, xin, w, strides=1, padding=1):
            del name
            s = (strides, strides)
            pad = ((padding, padding), (padding, padding))
            ci = w.shape[-2]
            if lowering == "pallas":
                # every conv routes through the custom-vjp bgc forward;
                # the vjp itself falls back to XLA's dW on the strided
                # ones (4 of 57 in ResNet-56)
                strat = "pallas"
            else:
                strat = ("bgc" if lowering == "bgc"
                         or (lowering == "auto" and ci <= BGC_MAX_CI)
                         else "blockdiag")
            return lane_conv(xin, w.astype(dtype), L, strides=s, padding=pad,
                             strategy=strat)

        def bn(name, xin):
            y, ra = lane_bn(xin, p[name], bs[name], L, train, dtype)
            new_bs[name] = ra
            return y

        def bn_in(block, name, xin):
            y, ra = lane_bn(xin, p[block][name], bs[block][name], L, train,
                            dtype)
            new_bs.setdefault(block, {})[name] = ra
            return y

        x = conv("conv1", x, p["conv1"]["kernel"])
        x = bn("bn1", x)
        x = jax.nn.relu(x)
        for stage, (_, strides) in enumerate([(16, 1), (32, 2), (64, 2)]):
            for block in range(n):
                name = f"layer{stage + 1}_block{block}"
                blk = p[name]
                s = strides if block == 0 else 1
                residual = x
                y = conv("conv1", x, blk["conv1"]["kernel"], strides=s)
                y = bn_in(name, "bn1", y)
                y = jax.nn.relu(y)
                y = conv("conv2", y, blk["conv2"]["kernel"])
                y = bn_in(name, "bn2", y)
                if "downsample_conv" in blk:
                    residual = conv("downsample", x,
                                    blk["downsample_conv"]["kernel"],
                                    strides=s, padding=0)
                    residual = bn_in(name, "downsample_bn", residual)
                x = jax.nn.relu(y + residual)
        x = jnp.mean(x, axis=(1, 2))  # [B, L*64]
        B = x.shape[0]
        feat = x.reshape(B, L, -1).astype(jnp.float32)
        # per-lane head: fc kernel [L, 64, classes], bias [L, classes]
        logits = (jnp.einsum("blc,lco->lbo", feat,
                             p["fc"]["kernel"].astype(jnp.float32))
                  + p["fc"]["bias"][:, None, :].astype(jnp.float32))
        return logits, new_bs

    return apply_fn


def _make_cnn_apply(model: CNNOriginalFedAvg, L: int):
    """Packed apply for :class:`CNNOriginalFedAvg` (``models/cnn.py``):
    conv5x5(32) + pool + conv5x5(64) + pool + dense512 + head, biased
    convs, no norm layers. The 1-input-channel stem merges ALL lanes
    into one dense conv (per-group K: 25 -> 25L); conv2 merges
    ``128//32 = 4`` lanes (K: 800 -> 3200, whole 128-wide tiles)."""
    dtype = model.dtype

    def apply_fn(stacked_vars, x, train=False):
        del train  # no dropout / batch stats in this family
        p = stacked_vars["params"]
        if x.ndim == 4:  # [L, B, 28, 28] -> add channel dim
            x = x[..., None]
        x = lane_merge(x.astype(dtype))  # [B, 28, 28, L*1]

        def biased_conv(name, xin, padding):
            w = p[name]["kernel"].astype(dtype)
            y = lane_conv(xin, w, L, strides=(1, 1), padding=padding)
            return y + p[name]["bias"].astype(dtype).reshape(-1)

        x = biased_conv("conv1", x, ((2, 2), (2, 2)))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))  # per merged channel
        x = biased_conv("conv2", x, ((2, 2), (2, 2)))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        # per-lane flatten in the reference's (H, W, C) order
        x = lane_unmerge(x, L)  # [L, B, H, W, C]
        x = x.reshape(x.shape[0], x.shape[1], -1)  # [L, B, HWC]
        h = jnp.einsum("lbi,lio->lbo", x,
                       p["fc1"]["kernel"].astype(dtype))
        h = nn.relu(h + p["fc1"]["bias"][:, None, :].astype(dtype))
        logits = (jnp.einsum("lbi,lio->lbo", h.astype(jnp.float32),
                             p["fc2"]["kernel"].astype(jnp.float32))
                  + p["fc2"]["bias"][:, None, :].astype(jnp.float32))
        return logits, {}

    return apply_fn


def make_lane_loss_builder(model, augment_fn=None, lowering="blockdiag"):
    """TrainSpec ``lane_loss_builder`` for classification over any
    :data:`PACKED_FAMILIES` model (see ``core/trainer.py``): called with
    the lane count, returns ``lane_loss_fn(stacked_state, batch,
    step_keys, train) -> (loss_sum, (new_stacked_state,
    per_lane_metrics))`` -- the whole-lane-block loss the packed
    LaneRunner differentiates in one program.

    Per-lane loss/metrics reproduce ``make_classification_spec`` exactly
    (masked mean CE, argmax-correct, count), just batched over the
    leading lane axis; ``loss_sum`` is the sum of per-lane losses, whose
    gradient w.r.t. the lane-stacked params is the per-lane gradients
    (lanes are computationally independent).
    """
    del augment_fn  # augmentation stays in the engine body (per-lane vmap)

    if not isinstance(model, CifarResNet) and lowering != "blockdiag":
        # only the ResNet family dispatches on the conv strategy; letting a
        # non-default request pass silently would label an A/B run "bgc"
        # while measuring blockdiag
        import logging
        logging.warning(
            "lane_lowering=%r is ignored for %s (only CifarResNet "
            "dispatches per-conv strategies); running the default lowering",
            lowering, type(model).__name__)

    def builder(L):
        packed_apply = (make_lane_packed_apply(model, L, lowering)
                        if isinstance(model, CifarResNet)
                        else make_lane_packed_apply(model, L))

        def lane_loss_fn(stacked_state, batch, rng, train):
            del rng  # no PACKED_FAMILIES model uses dropout rngs
            logits, new_bs = packed_apply(stacked_state, batch["x"], train)
            y, mask = batch["y"], batch["mask"]  # [L, B]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(
                logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
            per_sample = -ll
            count = jnp.sum(mask, axis=1)  # [L]
            loss_sum_l = jnp.sum(per_sample * mask, axis=1)
            loss_l = loss_sum_l / jnp.maximum(count, 1.0)
            correct = jnp.sum(
                (jnp.argmax(logits, axis=-1) == y) * mask, axis=1)
            metrics = {"loss_sum": loss_sum_l, "correct": correct,
                       "count": count}
            new_state = dict(stacked_state)
            if new_bs:  # stat-free families (the CNN) return {}
                new_state["batch_stats"] = new_bs
            return jnp.sum(loss_l), (new_state, metrics)

        return lane_loss_fn

    return builder


#: model families with a lane-packed lowering -- the ONE list to extend
#: (both the apply dispatch and the spec-facing registry derive from it)
PACKED_FAMILIES = (CifarResNet, CNNOriginalFedAvg)


def builder_for(model, lowering=None):
    """Registry: the packed-lowering ``lane_loss_builder`` for a model
    instance, or None when the family has no lane-packed apply. Spec
    builders call this instead of type-checking models themselves.
    ``lowering`` overrides the conv strategy (default ``"blockdiag"``,
    the lowering behind the measured 114.5 rph flagship number; the r5
    per-layer shoot-out puts ``bgc`` within noise of it, so the default
    only moves on a full-model A/B win). An explicit ``lowering`` for a
    family that does not dispatch on it logs a warning (see
    ``make_lane_loss_builder``) rather than silently mislabeling A/B
    runs."""
    if isinstance(model, PACKED_FAMILIES):
        return make_lane_loss_builder(
            model, lowering=lowering or "blockdiag")
    return None


__all__ = ["lane_merge", "lane_unmerge", "merged_to_stacked", "lane_conv",
           "lane_conv_bgc", "lane_bn", "make_lane_packed_apply",
           "make_lane_loss_builder", "builder_for", "MXU_K", "BGC_MAX_CI"]
