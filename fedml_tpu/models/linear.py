"""Linear models. Parity: reference ``fedml_api/model/linear/lr.py:4-11``."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    """Single dense layer. The reference applies a sigmoid at the output and
    then feeds it to CrossEntropyLoss (``lr.py:10-11`` -- a quirk it inherited
    from LEAF); ``apply_sigmoid=True`` reproduces that exactly so accuracy
    curves are comparable. Default returns plain logits.
    """
    num_classes: int
    apply_sigmoid: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        logits = nn.Dense(self.num_classes, name="linear")(x)
        if self.apply_sigmoid:
            return nn.sigmoid(logits)
        return logits


class DenseModel(nn.Module):
    """Dense head used by vertical FL (reference
    ``fedml_api/model/finance/vfl_models_standalone.py``): a linear layer with
    optional bias, trained by exchanged gradients rather than local loss."""
    output_dim: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.output_dim, use_bias=self.use_bias, name="dense")(x)


class LocalModel(nn.Module):
    """Feature extractor for a vertical-FL party (reference
    ``vfl_models_standalone.py`` LocalModel: dense -> relu stack)."""
    hidden_dims: tuple = (32,)
    output_dim: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, h in enumerate(self.hidden_dims):
            x = nn.relu(nn.Dense(h, name=f"hidden_{i}")(x))
        return nn.Dense(self.output_dim, name="out")(x)
