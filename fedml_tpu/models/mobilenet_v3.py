"""MobileNetV3 LARGE/SMALL. Parity: reference
``fedml_api/model/cv/mobilenet_v3.py:137`` (``MobileNetV3(model_mode=
"LARGE"|"SMALL", num_classes, multiplier, dropout_rate)``).

TPU notes: depthwise convs use ``feature_group_count`` so XLA maps them onto
the MXU; h-swish/h-sigmoid are cheap elementwise ops XLA fuses into the
surrounding convs.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def h_sigmoid(x):
    """Reference ``mobilenet_v3.py:35-41`` (relu6(x+3)/6)."""
    return nn.relu6(x + 3.0) / 6.0


def h_swish(x):
    """Reference ``mobilenet_v3.py:44-50`` (x * h_sigmoid(x))."""
    return x * h_sigmoid(x)


def _make_divisible(v, divisor=8, min_value=None):
    """Channel rounding, reference ``mobilenet_v3.py:54-61``."""
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcite(nn.Module):
    """SE block with h-sigmoid gate (reference ``SqueezeBlock``,
    ``mobilenet_v3.py:64-81``, divide=4)."""
    channels: int
    divide: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(self.channels // self.divide, dtype=self.dtype,
                             name="fc1")(s))
        s = h_sigmoid(nn.Dense(self.channels, dtype=self.dtype,
                               name="fc2")(s))
        return x * s[:, None, None, :]


class _Bneck(nn.Module):
    """Inverted-residual bottleneck (reference ``MobileBlock``,
    ``mobilenet_v3.py:84-135``)."""
    kernel: int
    exp_size: int
    out_channels: int
    use_se: bool
    use_hs: bool  # h-swish if True else ReLU
    strides: int
    norm: Any
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        act = h_swish if self.use_hs else nn.relu
        in_ch = x.shape[-1]
        y = x
        if self.exp_size != in_ch:
            y = nn.Conv(self.exp_size, (1, 1), use_bias=False,
                        dtype=self.dtype, name="expand")(y)
            y = act(self.norm(name="bn1")(y))
        y = nn.Conv(self.exp_size, (self.kernel, self.kernel),
                    strides=self.strides, padding=self.kernel // 2,
                    feature_group_count=self.exp_size, use_bias=False,
                    dtype=self.dtype, name="dw")(y)
        y = act(self.norm(name="bn2")(y))
        if self.use_se:
            y = SqueezeExcite(self.exp_size, dtype=self.dtype, name="se")(y)
        y = nn.Conv(self.out_channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="project")(y)
        y = self.norm(name="bn3")(y)
        if self.strides == 1 and in_ch == self.out_channels:
            y = y + x
        return y


# (kernel, exp_size, out, SE, h-swish, stride) -- paper Table 1/2, matching
# the reference's layer settings (mobilenet_v3.py:137-243).
_LARGE: Sequence[Tuple[int, int, int, bool, bool, int]] = [
    (3, 16, 16, False, False, 1),
    (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1),
    (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1),
    (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2),
    (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1),
    (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1),
    (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2),
    (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
]
_SMALL: Sequence[Tuple[int, int, int, bool, bool, int]] = [
    (3, 16, 16, True, False, 2),
    (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1),
    (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1),
    (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1),
    (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2),
    (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1),
]


class MobileNetV3(nn.Module):
    """Reference ``MobileNetV3`` (``mobilenet_v3.py:137-265``)."""
    model_mode: str = "LARGE"
    num_classes: int = 1000
    multiplier: float = 1.0
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        mode = self.model_mode.upper()
        if mode not in ("LARGE", "SMALL"):
            raise ValueError(f"model_mode must be LARGE or SMALL, got "
                             f"{self.model_mode!r}")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        cfg = _LARGE if mode == "LARGE" else _SMALL
        last_exp = 960 if mode == "LARGE" else 576
        x = x.astype(self.dtype)

        stem = _make_divisible(16 * self.multiplier)
        x = nn.Conv(stem, (3, 3), strides=2, padding=1, use_bias=False,
                    dtype=self.dtype, name="stem")(x)
        x = h_swish(norm(name="bn_stem")(x))
        for i, (k, e, c, se, hs, s) in enumerate(cfg):
            x = _Bneck(k, _make_divisible(e * self.multiplier),
                       _make_divisible(c * self.multiplier), se, hs, s, norm,
                       dtype=self.dtype, name=f"bneck{i}")(x)
        head = _make_divisible(last_exp * self.multiplier)
        x = nn.Conv(head, (1, 1), use_bias=False, dtype=self.dtype,
                    name="head_conv")(x)
        x = h_swish(norm(name="bn_head")(x))
        x = jnp.mean(x, axis=(1, 2))
        x = h_swish(nn.Dense(1280, dtype=self.dtype, name="head_fc")(x))
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="classifier")(x.astype(jnp.float32))
