"""MobileNet v1 (depthwise separable). Parity: reference
``fedml_api/model/cv/mobilenet.py:60,207`` (standard 13-block v1, width 1.0).
Depthwise convs use ``feature_group_count`` so XLA lowers them onto the MXU.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

# (filters, stride) per depthwise-separable block, standard MobileNet v1
_CFG = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1)]


class _DepthwiseSeparable(nn.Module):
    filters: int
    strides: int
    norm: Any
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), strides=self.strides, padding=1,
                    feature_group_count=in_ch, use_bias=False,
                    dtype=self.dtype, name="dw")(x)
        x = nn.relu(self.norm(name="bn1")(x))
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    name="pw")(x)
        return nn.relu(self.norm(name="bn2")(x))


class MobileNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        from functools import partial
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), strides=1, padding=1, use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        x = nn.relu(norm(name="bn1")(x))
        for i, (filters, strides) in enumerate(_CFG):
            x = _DepthwiseSeparable(filters, strides, norm, dtype=self.dtype,
                                    name=f"block{i}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))
