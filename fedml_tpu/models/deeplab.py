"""DeepLab-v3+-style semantic segmentation model in Flax.

Parity target: the reference FedSeg experiments parameterize a
DeepLab-style net by ``--backbone`` and ``--outstride``
(``fedml_api/distributed/fedseg`` args; SURVEY.md section 2.2). This is a
TPU-first re-design, not a port: NHWC layout, atrous (dilated) convs for
the output stride, an ASPP pyramid with global pooling, and a light
decoder with an encoder skip -- all static shapes so XLA tiles every conv
onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def _bilinear(x, hw):
    import jax
    return jax.image.resize(x, (x.shape[0], hw[0], hw[1], x.shape[-1]),
                            method="bilinear")


class _ConvBlock(nn.Module):
    features: int
    kernel: int = 3
    strides: int = 1
    dilation: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (self.kernel, self.kernel),
                    strides=self.strides,
                    kernel_dilation=(self.dilation, self.dilation),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype)(x)
        return nn.relu(x)


class _Backbone(nn.Module):
    """Small dilated residual encoder. ``output_stride`` 16 or 8 controls
    where striding stops and dilation takes over (DeepLab's atrous trick)."""
    width: int = 32
    output_stride: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        blk = partial(_ConvBlock, dtype=self.dtype)
        x = blk(self.width, strides=2)(x, train)            # /2
        low = blk(self.width * 2, strides=2)(x, train)      # /4 (skip)
        x = blk(self.width * 4, strides=2)(low, train)      # /8
        if self.output_stride == 16:
            x = blk(self.width * 8, strides=2)(x, train)    # /16
            x = blk(self.width * 8, dilation=2)(x, train)
        else:  # output_stride 8: dilate instead of stride
            x = blk(self.width * 8, dilation=2)(x, train)
            x = blk(self.width * 8, dilation=4)(x, train)
        return x, low


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling: parallel dilated 3x3s + 1x1 + global
    pooling, concatenated and projected."""
    features: int = 128
    rates: tuple = (6, 12, 18)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        blk = partial(_ConvBlock, dtype=self.dtype)
        branches = [blk(self.features, kernel=1)(x, train)]
        for r in self.rates:
            branches.append(blk(self.features, dilation=r)(x, train))
        gp = jnp.mean(x, axis=(1, 2), keepdims=True)
        gp = blk(self.features, kernel=1)(gp, train)
        gp = jnp.broadcast_to(gp, branches[0].shape)
        x = jnp.concatenate(branches + [gp], axis=-1)
        return blk(self.features, kernel=1)(x, train)


class DeepLab(nn.Module):
    """Encoder + ASPP + decoder-with-skip; logits upsampled to input size.

    Flags mirror the reference (``--backbone`` width preset,
    ``--outstride`` in {8, 16}).
    """
    num_classes: int = 21
    backbone: str = "resnet"     # "resnet" (width 32) | "mobilenet" (width 16)
    output_stride: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        width = 32 if self.backbone == "resnet" else 16
        feats, low = _Backbone(width=width, output_stride=self.output_stride,
                               dtype=self.dtype)(x, train)
        feats = ASPP(features=width * 4, dtype=self.dtype)(feats, train)
        # decoder: upsample to the skip's resolution, fuse, refine
        feats = _bilinear(feats, low.shape[1:3])
        low = _ConvBlock(width, kernel=1, dtype=self.dtype)(low, train)
        feats = jnp.concatenate([feats, low], axis=-1)
        feats = _ConvBlock(width * 4, dtype=self.dtype)(feats, train)
        logits = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32)(feats)
        return _bilinear(logits, x.shape[1:3])
