"""Split ResNets for FedGKT (reference ``fedml_api/model/cv/resnet56_gkt/``:
``resnet_client.py:206,230`` define resnet5_56 / resnet8_56 -- a stem + one
16-channel stage + a local classification head that also exposes the feature
maps; ``resnet_server.py:200`` defines resnet56_server -- the remaining 32/64
channel stages consuming those features).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.resnet import BasicBlock


class GKTClientResNet(nn.Module):
    """Small edge model: stem + ``n_blocks`` 16-channel blocks. Returns
    ``(features [B,H,W,16], logits [B,classes])`` -- the two payloads the
    client uploads (reference ``GKTClientTrainer.py:108-129``)."""
    n_blocks: int = 1  # 1 -> resnet5_56, 2 -> resnet8_56
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        x = nn.relu(norm(name="bn1")(x))
        for b in range(self.n_blocks):
            x = BasicBlock(16, 1, norm, dtype=self.dtype, name=f"block{b}")(x)
        features = x
        pooled = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(pooled)
        return features, logits


class GKTServerResNet(nn.Module):
    """Large server model consuming client feature maps: the 32/64-channel
    stages of ResNet-56 (reference ``resnet_server.py:200``)."""
    n: int = 9  # blocks per stage (9 -> ResNet-56 tail)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = features.astype(self.dtype)
        for stage, (filters, strides) in enumerate([(32, 2), (64, 2)]):
            for b in range(self.n):
                x = BasicBlock(filters, strides if b == 0 else 1, norm,
                               dtype=self.dtype,
                               name=f"layer{stage + 2}_block{b}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


def resnet5_56(class_num=10, **kw):
    return GKTClientResNet(n_blocks=1, num_classes=class_num, **kw)


def resnet8_56(class_num=10, **kw):
    return GKTClientResNet(n_blocks=2, num_classes=class_num, **kw)


def resnet56_server(class_num=10, **kw):
    return GKTServerResNet(n=9, num_classes=class_num, **kw)
