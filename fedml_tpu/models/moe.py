"""Mixture-of-Experts transformer (Switch-style top-1 routing).

Net-new capability for the TPU rebuild (the reference has no conditional
computation anywhere): a drop-in replacement for the Transformer MLP where
each token routes to one of ``n_experts`` expert MLPs. TPU-first design:
routing is FIXED-CAPACITY einsum dispatch -- a one-hot ``[tokens, E, C]``
combine tensor instead of ragged gather/scatter, so shapes stay static,
everything is a batched matmul on the MXU, and the expert dimension is a
plain array axis that shards over an ``expert`` mesh axis (ep; see
:mod:`fedml_tpu.parallel.expert_parallel` and
``__graft_entry__.dryrun_multichip`` case 9).

Tokens overflowing an expert's capacity are dropped (their block output is
the residual identity) -- the standard Switch trade; the auxiliary
load-balancing loss (sown into the ``losses`` collection) keeps drops
rare. The attention sublayer is shared with the dense transformer via
``_Block``'s ``mlp_factory`` seam -- one attention implementation.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.transformer import _Block


class MoEMLP(nn.Module):
    """Top-1 routed expert MLP over flattened tokens.

    Input ``[N, C]`` -> output ``[N, C]``; the Switch load-balancing aux
    loss is sown as ``losses/moe_aux`` (collect with
    ``apply(..., mutable=['losses'])``). Expert params are stacked on a
    leading ``E`` axis (``wi [E, C, H]``, ``wo [E, H, C]``) so ep sharding
    is a PartitionSpec on that axis.
    """
    n_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        N, C = x.shape
        E = self.n_experts
        H = self.mlp_ratio * C
        cap = max(1, int(self.capacity_factor * N / E))

        gates = jax.nn.softmax(
            nn.Dense(E, dtype=jnp.float32, name="router")(
                x.astype(jnp.float32)))                    # [N, E]
        expert = jnp.argmax(gates, axis=-1)                # [N]
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
        # position of each token within its expert's queue
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0    # [N, E]
        keep = (pos >= 0) & (pos < cap)
        # dispatch/combine tensor [N, E, C(ap)]
        disp = (onehot * keep)[:, :, None] * jax.nn.one_hot(
            jnp.clip(pos, 0, cap - 1).astype(jnp.int32), cap,
            dtype=jnp.float32)
        gate_val = jnp.sum(gates * onehot * keep, axis=-1)  # [N]

        wi = self.param("wi", nn.initializers.lecun_normal(), (E, C, H),
                        jnp.float32).astype(self.dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(), (E, H, C),
                        jnp.float32).astype(self.dtype)
        # route tokens into per-expert buffers, run the expert MLPs as one
        # batched matmul pair, and combine back -- all einsums
        xin = jnp.einsum("nec,nd->ecd", disp.astype(self.dtype),
                         x.astype(self.dtype))              # [E, C(ap), C]
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xin, wi))
        out = jnp.einsum("ech,ehd->ecd", h, wo)             # [E, Cap, C]
        y = jnp.einsum("nec,ecd->nd", disp.astype(self.dtype),
                       out) * gate_val[:, None].astype(self.dtype)

        # Switch aux loss: E * sum_e (fraction routed to e) * (mean gate e)
        frac = jnp.mean(onehot, axis=0)
        mean_gate = jnp.mean(gates, axis=0)
        self.sow("losses", "moe_aux", E * jnp.sum(frac * mean_gate))
        return y.astype(x.dtype)


def MoEBlock(n_heads, n_experts=8, mlp_ratio=4, capacity_factor=1.25,
             dtype=jnp.float32, attention_fn=None, **kw):
    """Transformer block with the MLP replaced by :class:`MoEMLP` --
    :class:`~fedml_tpu.models.transformer._Block` with an MoE
    ``mlp_factory`` (shared attention implementation)."""
    return _Block(n_heads, mlp_ratio, dtype, attention_fn,
                  mlp_factory=partial(MoEMLP, n_experts, mlp_ratio,
                                      capacity_factor, dtype), **kw)


class MoETransformerLM(nn.Module):
    """Causal LM with MoE blocks: same surface as
    :class:`fedml_tpu.models.transformer.TransformerLM` (token ids
    ``[B, T]`` -> logits ``[B, T, vocab]``), MoE aux losses sown into the
    ``losses`` collection (apply with ``mutable=['losses']`` to collect)."""
    vocab_size: int
    n_layers: int = 4
    n_heads: int = 4
    d_model: int = 256
    max_len: int = 2048
    n_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    attention_fn: Optional[Any] = None

    @nn.compact
    def __call__(self, idx, train: bool = False):
        B, T = idx.shape
        tok = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                       name="tok_embed")(idx)
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(T)[None])
        x = tok + pos
        for i in range(self.n_layers):
            x = MoEBlock(self.n_heads, self.n_experts, self.mlp_ratio,
                         self.capacity_factor, self.dtype,
                         self.attention_fn, name=f"block{i}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


__all__ = ["MoEMLP", "MoEBlock", "MoETransformerLM"]
