"""FedAvg-paper CNNs. Parity: reference ``fedml_api/model/cv/cnn.py``.

``CNNOriginalFedAvg`` must have exactly 1,663,370 parameters with
``only_digits=True`` (reference docstring ``cnn.py:10-12``); the unit tests
assert this. Inputs are NHWC ``[B, 28, 28]`` or ``[B, 28, 28, 1]``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def _ensure_nhwc(x):
    if x.ndim == 3:
        x = x[..., None]
    return x


class CNNOriginalFedAvg(nn.Module):
    """2x(conv5x5 + maxpool) + 512-dense (reference ``cnn.py:5-69``)."""
    only_digits: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_nhwc(x).astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding=2, dtype=self.dtype, name="conv1")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding=2, dtype=self.dtype, name="conv2")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype, name="fc1")(x))
        return nn.Dense(10 if self.only_digits else 62, dtype=jnp.float32,
                        name="fc2")(x.astype(jnp.float32))


class CNNDropOut(nn.Module):
    """Adaptive-Federated-Optimization EMNIST CNN (reference ``cnn.py:72-``):
    conv3x3(32) -> conv3x3(64) -> maxpool -> dropout .25 -> dense 128 ->
    dropout .5 -> head. 1,199,882 params with ``only_digits=True``."""
    only_digits: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_nhwc(x).astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype,
                            name="conv1")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype,
                            name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else 62, dtype=jnp.float32,
                        name="fc2")(x.astype(jnp.float32))
