"""EfficientNet (b0-b7) in Flax. Parity: reference
``fedml_api/model/cv/efficientnet.py:138`` (``EfficientNet.from_name``) and
``efficientnet_utils.py`` (swish, drop-connect, compound width/depth scaling,
``round_filters``/``round_repeats`` at ``efficientnet_utils.py:79-110``).

TPU notes: MBConv is expressed as 1x1 expand -> depthwise (``feature_group_
count``) -> SE -> 1x1 project, all MXU-friendly; swish and the SE gate fuse
into the convs under XLA. Drop-connect is per-sample stochastic depth drawn
from the ``dropout`` RNG collection at train time.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BlockArgs(NamedTuple):
    """One MBConv stage (reference ``BlockArgs``,
    ``efficientnet_utils.py:45-47``)."""
    kernel: int
    strides: int
    expand_ratio: int
    in_filters: int
    out_filters: int
    num_repeat: int
    se_ratio: float = 0.25


# EfficientNet-B0 baseline stages (reference block-string decode,
# ``efficientnet_utils.py`` blocks_args for b0).
_B0_BLOCKS: Sequence[BlockArgs] = [
    BlockArgs(3, 1, 1, 32, 16, 1),
    BlockArgs(3, 2, 6, 16, 24, 2),
    BlockArgs(5, 2, 6, 24, 40, 2),
    BlockArgs(3, 2, 6, 40, 80, 3),
    BlockArgs(5, 1, 6, 80, 112, 3),
    BlockArgs(5, 2, 6, 112, 192, 4),
    BlockArgs(3, 1, 6, 192, 320, 1),
]

# name -> (width_coef, depth_coef, dropout) (reference
# ``efficientnet_utils.py`` efficientnet_params table).
_PARAMS = {
    "efficientnet-b0": (1.0, 1.0, 0.2),
    "efficientnet-b1": (1.0, 1.1, 0.2),
    "efficientnet-b2": (1.1, 1.2, 0.3),
    "efficientnet-b3": (1.2, 1.4, 0.3),
    "efficientnet-b4": (1.4, 1.8, 0.4),
    "efficientnet-b5": (1.6, 2.2, 0.4),
    "efficientnet-b6": (1.8, 2.6, 0.5),
    "efficientnet-b7": (2.0, 3.1, 0.5),
}


def round_filters(filters: int, width_coef: float, divisor: int = 8) -> int:
    """Width scaling (reference ``efficientnet_utils.py:79-102``); same
    divisor rounding rule as MobileNetV3's channel rounding."""
    from fedml_tpu.models.mobilenet_v3 import _make_divisible
    return _make_divisible(filters * width_coef, divisor)


def round_repeats(repeats: int, depth_coef: float) -> int:
    """Depth scaling (reference ``efficientnet_utils.py:105-110``)."""
    return int(-(-depth_coef * repeats // 1))  # ceil


def drop_connect(x, rng, rate: float):
    """Per-sample stochastic depth (reference
    ``efficientnet_utils.py`` drop_connect)."""
    from fedml_tpu.models.layers import drop_path
    return drop_path(x, rng, rate)


class MBConvBlock(nn.Module):
    """Mobile inverted bottleneck + SE (reference ``MBConvBlock``,
    ``efficientnet.py:36-135``)."""
    kernel: int
    strides: int
    expand_ratio: int
    out_filters: int
    se_ratio: float
    norm: Any
    drop_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        mid = in_ch * self.expand_ratio
        y = x
        if self.expand_ratio != 1:
            y = nn.Conv(mid, (1, 1), use_bias=False, dtype=self.dtype,
                        name="expand")(y)
            y = nn.swish(self.norm(name="bn0")(y))
        y = nn.Conv(mid, (self.kernel, self.kernel), strides=self.strides,
                    padding=self.kernel // 2, feature_group_count=mid,
                    use_bias=False, dtype=self.dtype, name="dw")(y)
        y = nn.swish(self.norm(name="bn1")(y))
        if self.se_ratio > 0:
            se_ch = max(1, int(in_ch * self.se_ratio))
            s = jnp.mean(y, axis=(1, 2))
            s = nn.swish(nn.Dense(se_ch, dtype=self.dtype,
                                  name="se_reduce")(s))
            s = nn.sigmoid(nn.Dense(mid, dtype=self.dtype,
                                    name="se_expand")(s))
            y = y * s[:, None, None, :]
        y = nn.Conv(self.out_filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="project")(y)
        y = self.norm(name="bn2")(y)
        if self.strides == 1 and in_ch == self.out_filters:
            if train and self.drop_rate > 0:
                y = drop_connect(y, self.make_rng("dropout"), self.drop_rate)
            y = y + x
        return y


class EfficientNet(nn.Module):
    """Reference ``EfficientNet`` (``efficientnet.py:138-302``); construct via
    :func:`efficientnet` (the ``from_name`` analog, ``efficientnet.py:305``)."""
    num_classes: int = 1000
    width_coef: float = 1.0
    depth_coef: float = 1.0
    dropout_rate: float = 0.2
    drop_connect_rate: float = 0.2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.99, epsilon=1e-3, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(round_filters(32, self.width_coef), (3, 3), strides=2,
                    padding=1, use_bias=False, dtype=self.dtype,
                    name="stem")(x)
        x = nn.swish(norm(name="bn_stem")(x))

        total = sum(round_repeats(b.num_repeat, self.depth_coef)
                    for b in _B0_BLOCKS)
        idx = 0
        for si, b in enumerate(_B0_BLOCKS):
            out_f = round_filters(b.out_filters, self.width_coef)
            for r in range(round_repeats(b.num_repeat, self.depth_coef)):
                # drop-connect rate scales linearly with depth
                # (reference efficientnet.py:291-294)
                rate = self.drop_connect_rate * idx / total
                x = MBConvBlock(b.kernel, b.strides if r == 0 else 1,
                                b.expand_ratio, out_f, b.se_ratio, norm,
                                drop_rate=rate, dtype=self.dtype,
                                name=f"block{si}_{r}")(x, train=train)
                idx += 1

        x = nn.Conv(round_filters(1280, self.width_coef), (1, 1),
                    use_bias=False, dtype=self.dtype, name="head")(x)
        x = nn.swish(norm(name="bn_head")(x))
        x = jnp.mean(x, axis=(1, 2))
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


def efficientnet(model_name: str = "efficientnet-b0", num_classes: int = 1000,
                 **kw) -> EfficientNet:
    """``EfficientNet.from_name`` analog (reference ``efficientnet.py:305-325``)."""
    if model_name not in _PARAMS:
        raise ValueError(
            f"model_name should be one of: {', '.join(sorted(_PARAMS))}")
    w, d, drop = _PARAMS[model_name]
    kw.setdefault("dropout_rate", drop)
    return EfficientNet(num_classes=num_classes, width_coef=w, depth_coef=d,
                        **kw)
