"""DARTS search space for FedNAS, TPU-native.

Capability parity with the reference search space (``fedml_api/model/cv/darts/
model_search.py:10,26,172`` MixedOp/Cell/Network, ``operations.py`` primitive
set, ``genotypes.py`` Genotype schema) re-designed for XLA:

- Architecture parameters (alpha) live in their own Flax collection ``arch``,
  so the bilevel split (weights vs architecture) is a pytree partition, not an
  optimizer bookkeeping exercise, and FedNAS's server-side averaging of BOTH
  weights and alpha (``FedNASAggregator.py:56-64,95-100``) is the same
  weighted tree-mean used for every other collection.
- A MixedOp evaluates all primitives and takes the softmax-weighted sum --
  dense compute with static shapes that XLA fuses and tiles onto the MXU;
  there is no data-dependent branching anywhere.
- The fixed (post-search) network applies drop-path as a per-sample Bernoulli
  mask (reference ``utils.drop_path``) using Flax's ``droppath`` rng stream.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)


class Genotype(NamedTuple):
    normal: Sequence[Tuple[str, int]]
    normal_concat: Sequence[int]
    reduce: Sequence[Tuple[str, int]]
    reduce_concat: Sequence[int]


# Published DARTS genotypes (schema of reference ``genotypes.py``) -- usable as
# fixed architectures without running a search.
DARTS_V1 = Genotype(
    normal=[("sep_conv_3x3", 1), ("sep_conv_3x3", 0), ("skip_connect", 0),
            ("sep_conv_3x3", 1), ("skip_connect", 0), ("sep_conv_3x3", 1),
            ("sep_conv_3x3", 0), ("skip_connect", 2)],
    normal_concat=[2, 3, 4, 5],
    reduce=[("max_pool_3x3", 0), ("max_pool_3x3", 1), ("skip_connect", 2),
            ("max_pool_3x3", 0), ("max_pool_3x3", 0), ("skip_connect", 2),
            ("skip_connect", 2), ("avg_pool_3x3", 0)],
    reduce_concat=[2, 3, 4, 5])


def _bn(train, affine=True, name=None):
    return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                        epsilon=1e-5, use_scale=affine, use_bias=affine,
                        name=name)


class ReLUConvBN(nn.Module):
    C_out: int
    kernel: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x, train):
        x = nn.relu(x)
        x = nn.Conv(self.C_out, (self.kernel, self.kernel),
                    strides=self.stride, padding="SAME", use_bias=False)(x)
        return _bn(train, affine=False)(x)


class FactorizedReduce(nn.Module):
    """Stride-2 reduction via two offset 1x1 convs (keeps all pixels)."""
    C_out: int

    @nn.compact
    def __call__(self, x, train):
        x = nn.relu(x)
        a = nn.Conv(self.C_out // 2, (1, 1), strides=2, use_bias=False)(x)
        b = nn.Conv(self.C_out - self.C_out // 2, (1, 1), strides=2,
                    use_bias=False)(x[:, 1:, 1:, :])
        # pad b back to a's spatial dims (odd inputs)
        pad_h = a.shape[1] - b.shape[1]
        pad_w = a.shape[2] - b.shape[2]
        b = jnp.pad(b, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        return _bn(train, affine=False)(jnp.concatenate([a, b], axis=-1))


class SepConv(nn.Module):
    """Two stacked depthwise-separable convs (reference ``operations.py``)."""
    C_out: int
    kernel: int
    stride: int

    @nn.compact
    def __call__(self, x, train):
        C_in = x.shape[-1]
        for i, (stride, cout) in enumerate([(self.stride, C_in),
                                            (1, self.C_out)]):
            x = nn.relu(x)
            x = nn.Conv(x.shape[-1], (self.kernel, self.kernel), strides=stride,
                        padding="SAME", feature_group_count=x.shape[-1],
                        use_bias=False, name=f"dw{i}")(x)
            x = nn.Conv(cout, (1, 1), use_bias=False, name=f"pw{i}")(x)
            x = _bn(train, affine=False, name=f"bn{i}")(x)
        return x


class DilConv(nn.Module):
    C_out: int
    kernel: int
    stride: int
    dilation: int = 2

    @nn.compact
    def __call__(self, x, train):
        x = nn.relu(x)
        x = nn.Conv(x.shape[-1], (self.kernel, self.kernel),
                    strides=self.stride, padding="SAME",
                    kernel_dilation=self.dilation,
                    feature_group_count=x.shape[-1], use_bias=False)(x)
        x = nn.Conv(self.C_out, (1, 1), use_bias=False)(x)
        return _bn(train, affine=False)(x)


class PoolOp(nn.Module):
    kind: str  # "max" | "avg"
    stride: int

    @nn.compact
    def __call__(self, x, train):
        if self.kind == "max":
            x = nn.max_pool(x, (3, 3), strides=(self.stride, self.stride),
                            padding="SAME")
        else:
            x = nn.avg_pool(x, (3, 3), strides=(self.stride, self.stride),
                            padding="SAME", count_include_pad=False)
        return _bn(train, affine=False)(x)


class ZeroOp(nn.Module):
    stride: int

    def __call__(self, x, train):
        if self.stride == 1:
            return jnp.zeros_like(x)
        return jnp.zeros_like(x[:, ::self.stride, ::self.stride, :])


class SkipOp(nn.Module):
    C_out: int
    stride: int

    @nn.compact
    def __call__(self, x, train):
        if self.stride == 1:
            return x
        return FactorizedReduce(self.C_out)(x, train)


def make_op(primitive: str, C: int, stride: int, name: str):
    if primitive == "none":
        return ZeroOp(stride, name=name)
    if primitive == "max_pool_3x3":
        return PoolOp("max", stride, name=name)
    if primitive == "avg_pool_3x3":
        return PoolOp("avg", stride, name=name)
    if primitive == "skip_connect":
        return SkipOp(C, stride, name=name)
    if primitive == "sep_conv_3x3":
        return SepConv(C, 3, stride, name=name)
    if primitive == "sep_conv_5x5":
        return SepConv(C, 5, stride, name=name)
    if primitive == "dil_conv_3x3":
        return DilConv(C, 3, stride, name=name)
    if primitive == "dil_conv_5x5":
        return DilConv(C, 5, stride, name=name)
    raise ValueError(primitive)


class MixedOp(nn.Module):
    C: int
    stride: int

    @nn.compact
    def __call__(self, x, weights, train):
        outs = [make_op(p, self.C, self.stride, name=p)(x, train)
                for p in PRIMITIVES]
        return sum(w * o for w, o in zip(weights, outs))


class SearchCell(nn.Module):
    """DARTS cell: 2 input nodes + ``steps`` intermediate nodes, every edge a
    MixedOp; output = channel-concat of the intermediate nodes."""
    C: int
    steps: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, weights, train):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C, name="pre0")(s0, train)
        else:
            s0 = ReLUConvBN(self.C, name="pre0")(s0, train)
        s1 = ReLUConvBN(self.C, name="pre1")(s1, train)
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            s = sum(
                MixedOp(self.C, 2 if self.reduction and j < 2 else 1,
                        name=f"edge{offset + j}")(
                    states[j], weights[offset + j], train)
                for j in range(len(states)))
            states.append(s)
            offset += len(states) - 1
        return jnp.concatenate(states[-self.steps:], axis=-1)


def n_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


class DARTSNetwork(nn.Module):
    """Search network (reference ``model_search.py:172`` Network).

    Alphas are ``arch`` collection variables ``alphas_normal`` /
    ``alphas_reduce`` of shape ``[n_edges, |PRIMITIVES|]``; softmax happens
    inside the forward pass, gradients flow to the ``arch`` collection.
    """
    C: int = 16
    layers: int = 8
    num_classes: int = 10
    steps: int = 4
    stem_multiplier: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        k = n_edges(self.steps)
        init = nn.initializers.normal(1e-3)
        a_n = self.variable("arch", "alphas_normal", init,
                            self.make_rng("params") if self.is_initializing()
                            else None, (k, len(PRIMITIVES)))
        a_r = self.variable("arch", "alphas_reduce", init,
                            self.make_rng("params") if self.is_initializing()
                            else None, (k, len(PRIMITIVES)))
        w_normal = jax.nn.softmax(a_n.value, axis=-1)
        w_reduce = jax.nn.softmax(a_r.value, axis=-1)

        C_curr = self.stem_multiplier * self.C
        s = nn.Conv(C_curr, (3, 3), padding=1, use_bias=False, name="stem")(x)
        s0 = s1 = _bn(train, name="stem_bn")(s)
        C_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = self.layers >= 3 and i in (self.layers // 3,
                                                   2 * self.layers // 3)
            if reduction:
                C_curr *= 2
            cell = SearchCell(C_curr, self.steps, reduction, reduction_prev,
                              name=f"cell{i}")
            s0, s1 = s1, cell(s0, s1, w_reduce if reduction else w_normal,
                              train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes, name="classifier")(out)


def derive_genotype(arch) -> Genotype:
    """Discretize alphas -> Genotype: per node keep the 2 strongest incoming
    edges (ranked by max non-``none`` weight), each with its best non-``none``
    primitive (reference ``model_search.py`` ``genotype()``)."""
    import numpy as np

    def parse(alphas):
        w = np.asarray(jax.nn.softmax(jnp.asarray(alphas), axis=-1))
        gene, start = [], 0
        steps = _steps_from_edges(w.shape[0])
        none_idx = PRIMITIVES.index("none")
        for i in range(steps):
            n_in = 2 + i
            rows = w[start:start + n_in]
            strength = np.max(np.delete(rows, none_idx, axis=1), axis=1)
            for j in np.argsort(-strength)[:2]:
                ops = rows[j].copy()
                ops[none_idx] = -1
                gene.append((PRIMITIVES[int(np.argmax(ops))], int(j)))
            start += n_in
        return gene, list(range(2, 2 + steps))[-4:] if steps >= 4 else list(
            range(2, 2 + steps))

    normal, n_cat = parse(arch["alphas_normal"])
    reduce, r_cat = parse(arch["alphas_reduce"])
    return Genotype(normal=normal, normal_concat=n_cat,
                    reduce=reduce, reduce_concat=r_cat)


def _steps_from_edges(k: int) -> int:
    steps, total = 0, 0
    while total < k:
        total += 2 + steps
        steps += 1
    assert total == k, f"invalid edge count {k}"
    return steps


class FixedCell(nn.Module):
    """Discrete cell from a genotype (reference train-stage ``model.py`` Cell)
    with per-sample drop-path on non-skip edges."""
    C: int
    genotype: Genotype
    reduction: bool
    reduction_prev: bool
    drop_path_prob: float = 0.0

    @nn.compact
    def __call__(self, s0, s1, train):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C, name="pre0")(s0, train)
        else:
            s0 = ReLUConvBN(self.C, name="pre0")(s0, train)
        s1 = ReLUConvBN(self.C, name="pre1")(s1, train)
        gene = self.genotype.reduce if self.reduction else self.genotype.normal
        concat = (self.genotype.reduce_concat if self.reduction
                  else self.genotype.normal_concat)
        states = [s0, s1]
        steps = len(gene) // 2
        for i in range(steps):
            outs = []
            for e in range(2):
                op_name, j = gene[2 * i + e]
                stride = 2 if self.reduction and j < 2 else 1
                h = make_op(op_name, self.C, stride,
                            name=f"node{i}_edge{e}_{op_name}")(states[j], train)
                if (train and self.drop_path_prob > 0.0
                        and op_name != "skip_connect"):
                    from fedml_tpu.models.layers import drop_path
                    h = drop_path(h, self.make_rng("droppath"),
                                  self.drop_path_prob)
                outs.append(h)
            states.append(outs[0] + outs[1])
        return jnp.concatenate([states[i] for i in concat], axis=-1)


class DARTSFixedNetwork(nn.Module):
    """Post-search evaluation network built from a Genotype (reference
    train-stage NetworkCIFAR; flags at ``main_fednas.py:44-99`` stage
    ``train``)."""
    genotype: Genotype = DARTS_V1
    C: int = 36
    layers: int = 8
    num_classes: int = 10
    stem_multiplier: int = 3
    drop_path_prob: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        C_curr = self.stem_multiplier * self.C
        s = nn.Conv(C_curr, (3, 3), padding=1, use_bias=False, name="stem")(x)
        s0 = s1 = _bn(train, name="stem_bn")(s)
        C_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = self.layers >= 3 and i in (self.layers // 3,
                                                   2 * self.layers // 3)
            if reduction:
                C_curr *= 2
            cell = FixedCell(C_curr, self.genotype, reduction, reduction_prev,
                             self.drop_path_prob, name=f"cell{i}")
            s0, s1 = s1, cell(s0, s1, train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes, name="classifier")(out)
