"""Shared layer helpers used across the model zoo."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def drop_path(x, rng, rate: float):
    """Per-sample stochastic depth: zero a sample's whole residual branch
    with probability ``rate``, rescaling survivors by 1/keep.

    One implementation for both reference variants -- EfficientNet's
    ``drop_connect`` (``efficientnet_utils.py``) and DARTS' ``drop_path``
    (``cv/darts/utils.py``); they are the same transform.
    """
    keep = 1.0 - rate
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(rng, keep, shape).astype(x.dtype)
    return x * mask / keep


__all__ = ["drop_path"]
