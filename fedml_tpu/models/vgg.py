"""VGG 11/13/16/19 with optional BatchNorm. Parity: reference
``fedml_api/model/cv/vgg.py:13,82-133`` (torchvision configs A/B/D/E)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

_CFGS = {
    "A": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "B": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "D": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"),
    "E": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
          512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: str = "A"
    batch_norm: bool = False
    num_classes: int = 10
    classifier_dims: Sequence[int] = (4096, 4096)
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv_i = 0
        for v in _CFGS[self.cfg]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=1, dtype=self.dtype,
                            name=f"conv{conv_i}")(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype, name=f"bn{conv_i}")(x)
                x = nn.relu(x)
                conv_i += 1
        x = x.reshape((x.shape[0], -1))
        for i, h in enumerate(self.classifier_dims):
            x = nn.relu(nn.Dense(h, dtype=self.dtype, name=f"fc{i}")(x))
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


def vgg11(class_num=10, batch_norm=False, **kw):
    return VGG(cfg="A", batch_norm=batch_norm, num_classes=class_num, **kw)


def vgg13(class_num=10, batch_norm=False, **kw):
    return VGG(cfg="B", batch_norm=batch_norm, num_classes=class_num, **kw)


def vgg16(class_num=10, batch_norm=False, **kw):
    return VGG(cfg="D", batch_norm=batch_norm, num_classes=class_num, **kw)


def vgg19(class_num=10, batch_norm=False, **kw):
    return VGG(cfg="E", batch_norm=batch_norm, num_classes=class_num, **kw)
