"""LSTM language models. Parity: reference ``fedml_api/model/nlp/rnn.py``.

- ``RNNOriginalFedAvg`` (``rnn.py:4-36``): 8-d embedding (vocab 90), 2x
  LSTM-256, dense head. ``output_all_timesteps=False`` predicts from the final
  hidden state (LEAF shakespeare); ``True`` emits per-position logits
  (fed_shakespeare, the commented variant at ``rnn.py:34-36``).
- ``RNNStackOverflow`` (``rnn.py:39-70``): vocab 10000+4 specials, 96-d
  embedding, LSTM-670, 96-d projection, tied-size output head.

LSTMs run via ``flax.linen.RNN`` over ``OptimizedLSTMCell`` -- an
``lax.scan`` whose per-step matmuls XLA fuses onto the MXU, replacing cuDNN
LSTM kernels.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class RNNOriginalFedAvg(nn.Module):
    embedding_dim: int = 8
    vocab_size: int = 90
    hidden_size: int = 256
    output_all_timesteps: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, input_seq, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embedding_dim, name="embeddings")(input_seq)
        x = x.astype(self.dtype)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype),
                   name="lstm1")(x)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype),
                   name="lstm2")(x)
        if not self.output_all_timesteps:
            x = x[:, -1]
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


class RNNStackOverflow(nn.Module):
    vocab_size: int = 10000
    num_oov_buckets: int = 1
    embedding_size: int = 96
    latent_size: int = 670
    num_layers: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, input_seq, train: bool = False):
        extended_vocab = self.vocab_size + 3 + self.num_oov_buckets
        x = nn.Embed(extended_vocab, self.embedding_size,
                     name="word_embeddings")(input_seq)
        x = x.astype(self.dtype)
        for i in range(self.num_layers):
            x = nn.RNN(nn.OptimizedLSTMCell(self.latent_size, dtype=self.dtype),
                       name=f"lstm{i + 1}")(x)
        x = nn.Dense(self.embedding_size, dtype=jnp.float32, name="fc1")(
            x.astype(jnp.float32))
        return nn.Dense(extended_vocab, dtype=jnp.float32, name="fc2")(x)
