"""CIFAR ResNets (BasicBlock, BatchNorm). Parity: reference
``fedml_api/model/cv/resnet.py:202,225`` (resnet56 / resnet110: 6n+2 layout,
channels 16/32/64, BN + identity-padding-free 1x1 downsample shortcut).

BatchNorm running statistics live in the ``batch_stats`` collection; FedAvg
averages them along with weights (the reference averages full state_dicts,
``FedAVGAggregator.py:72-83``) while defenses exclude them
(``fedml_tpu.core.robust``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    norm: ModuleDef = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # dtype threads into every conv: with bf16 it casts the fp32 params
        # to bf16 at apply time so the MXU runs 1-pass bf16 matmuls (fp32
        # convs are ~6x slower); master params/optimizer stay fp32
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=self.strides, padding=1,
                 name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding=1, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=self.strides,
                            name="downsample_conv")(x)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """6n+2 CIFAR ResNet; ``depth`` in {20, 32, 44, 56, 110}."""
    depth: int = 56
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        assert (self.depth - 2) % 6 == 0, "depth must be 6n+2"
        n = (self.depth - 2) // 6
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        x = norm(name="bn1")(x)
        x = nn.relu(x)
        for stage, (filters, strides) in enumerate([(16, 1), (32, 2), (64, 2)]):
            for block in range(n):
                x = BasicBlock(filters, strides if block == 0 else 1, norm,
                               dtype=self.dtype,
                               name=f"layer{stage + 1}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


def resnet56(class_num=10, **kw):
    return CifarResNet(depth=56, num_classes=class_num, **kw)


def resnet110(class_num=10, **kw):
    return CifarResNet(depth=110, num_classes=class_num, **kw)
