"""Decoder-only Transformer LM for federated next-word prediction.

Capability upgrade over the reference's sequence models (2-layer LSTMs over
80-char/20-token windows, ``fedml_api/model/nlp/rnn.py:4-70``): same
task surface (Shakespeare / StackOverflow NWP -- token ids in, next-token
logits out, so it drops into the existing ``TrainSpec`` seams and data
loaders), but attention-based and built on :mod:`fedml_tpu.ops`:

- single-device: fused Pallas flash attention
  (:func:`fedml_tpu.ops.pallas_attention.flash_attention`);
- long-context: pass ``attention_fn=make_ring_attention(mesh, ...)`` to
  shard the sequence over a mesh axis with K/V rotating over ICI
  (:mod:`fedml_tpu.ops.ring_attention`).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.ops.pallas_attention import flash_attention


class _Block(nn.Module):
    """Pre-LN transformer block. ``mlp_factory`` (e.g. a bound
    :class:`fedml_tpu.models.moe.MoEMLP`) swaps the dense MLP for an
    alternative operating on flattened ``[B*T, C]`` tokens -- THE seam
    that keeps exactly one attention implementation across the dense and
    MoE transformers."""
    n_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None
    mlp_factory: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T, C = x.shape
        D = C // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        qkv = nn.Dense(3 * C, use_bias=False, dtype=self.dtype,
                       name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (B, T, self.n_heads, D)
        if self.attention_fn is not None:
            att = self.attention_fn(q.reshape(shp), k.reshape(shp),
                                    v.reshape(shp))
        else:
            att = flash_attention(q.reshape(shp), k.reshape(shp),
                                  v.reshape(shp), True)
        att = att.reshape(B, T, C)
        x = x + nn.Dense(C, use_bias=False, dtype=self.dtype,
                         name="proj")(att)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        if self.mlp_factory is not None:
            y = self.mlp_factory(name="moe")(h.reshape(B * T, C))
            return x + y.reshape(B, T, C)
        h = nn.gelu(nn.Dense(self.mlp_ratio * C, dtype=self.dtype,
                             name="mlp_up")(h))
        return x + nn.Dense(C, dtype=self.dtype, name="mlp_down")(h)


class TransformerLM(nn.Module):
    """Causal LM over token ids ``[B, T] -> logits [B, T, vocab]``.

    ``attention_fn(q, k, v) -> out`` (all ``[B, T, H, D]``) overrides the
    attention implementation -- plug in
    ``make_ring_attention(mesh, causal=True)`` for sequence parallelism.
    """
    vocab_size: int
    n_layers: int = 4
    n_heads: int = 4
    d_model: int = 256
    max_len: int = 2048
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, idx, train: bool = False):
        B, T = idx.shape
        tok = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                       name="tok_embed")(idx)
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(T)[None])
        x = tok + pos
        for i in range(self.n_layers):
            x = _Block(self.n_heads, self.mlp_ratio, self.dtype,
                       self.attention_fn, name=f"block{i}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


def lm_loss(logits, tgt):
    """Masked next-token NLL: mean over positions with ``tgt >= 0``.

    THE loss convention shared by every LM training path (sp / tp / pp
    steps, their oracles in tests and the multichip dryrun) -- keep one
    definition so the implementations and their oracles cannot drift.
    """
    lp = jax.nn.log_softmax(logits.astype(jnp.float32))
    mask = (tgt >= 0).astype(jnp.float32)
    nll = -jnp.take_along_axis(
        lp, jnp.maximum(tgt, 0)[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def transformer_nwp(vocab_size: int = 10004, **kw):
    """StackOverflow-NWP-shaped config (vocab 10000 + 4 specials, matching
    ``fedml_tpu.data.stackoverflow``)."""
    return TransformerLM(vocab_size=vocab_size, **kw)


__all__ = ["TransformerLM", "transformer_nwp", "lm_loss"]
