"""Model factory: name -> Flax module, mirroring the reference's
``create_model`` switch (``fedml_experiments/distributed/fedavg/
main_fedavg.py:217-252``) so reference run commands translate 1:1.
"""

from __future__ import annotations

import logging


def create_model(args, model_name, output_dim):
    """Return an uninitialized Flax module for ``model_name``.

    Accepted names (reference ``main_fedavg.py:217-252`` plus aliases):
    lr, cnn, cnn_dropout, resnet56, resnet110, resnet18_gn, resnet34_gn,
    resnet50_gn, mobilenet, mobilenet_v3, efficientnet[-b0..b7],
    vgg11/13/16/19, rnn (shakespeare LSTM), rnn_stackoverflow.
    """
    from fedml_tpu import models

    logging.info("create_model. model_name = %s, output_dim = %s",
                 model_name, output_dim)
    group_norm = getattr(args, "group_norm_channels", 32) if args else 32
    only_digits = output_dim == 10
    # --model_dtype bf16: compute-dtype for the zoo (master params stay
    # fp32; convs/matmuls run 1-pass bf16 on the MXU -- the single biggest
    # single-chip throughput knob, see docs/PERFORMANCE.md)
    dt = {}
    dt_name = getattr(args, "model_dtype", None) if args else None
    if dt_name in ("bf16", "bfloat16"):
        import jax.numpy as jnp
        dt = {"dtype": jnp.bfloat16}

    if model_name == "lr":
        return models.LogisticRegression(num_classes=output_dim)
    if model_name == "cnn":
        return models.CNNOriginalFedAvg(only_digits=only_digits, **dt)
    if model_name == "cnn_dropout":
        return models.CNNDropOut(only_digits=only_digits, **dt)
    if model_name == "resnet56":
        return models.resnet56(class_num=output_dim, **dt)
    if model_name == "resnet110":
        return models.resnet110(class_num=output_dim, **dt)
    if model_name == "resnet18_gn":
        return models.resnet18_gn(class_num=output_dim, group_norm=group_norm,
                                  **dt)
    if model_name == "resnet34_gn":
        return models.resnet34_gn(class_num=output_dim, group_norm=group_norm,
                                  **dt)
    if model_name == "resnet50_gn":
        return models.resnet50_gn(class_num=output_dim, group_norm=group_norm,
                                  **dt)
    if model_name == "mobilenet":
        return models.MobileNet(num_classes=output_dim, **dt)
    if model_name == "mobilenet_v3":
        mode = getattr(args, "model_mode", "LARGE") if args else "LARGE"
        return models.MobileNetV3(model_mode=mode, num_classes=output_dim,
                                  **dt)
    if model_name.startswith("efficientnet"):
        name = "efficientnet-b0" if model_name == "efficientnet" else model_name
        return models.efficientnet(name, num_classes=output_dim, **dt)
    if model_name in ("vgg11", "vgg13", "vgg16", "vgg19"):
        fn = getattr(models, model_name)
        return fn(class_num=output_dim,
                  batch_norm=getattr(args, "vgg_bn", False) if args else False,
                  **dt)
    if model_name == "rnn":
        return models.RNNOriginalFedAvg(vocab_size=output_dim)
    if model_name == "rnn_fed_shakespeare":
        return models.RNNOriginalFedAvg(vocab_size=output_dim,
                                        output_all_timesteps=True)
    if model_name == "rnn_stackoverflow":
        return models.RNNStackOverflow(vocab_size=output_dim - 4)
    if model_name in ("transformer", "transformer_nwp"):
        return models.transformer_nwp(vocab_size=output_dim, **dt)
    if model_name == "moe_transformer":
        experts = getattr(args, "moe_experts", 8) if args else 8
        return models.MoETransformerLM(vocab_size=output_dim,
                                       n_experts=experts, **dt)
    raise ValueError(f"unknown model: {model_name}")
