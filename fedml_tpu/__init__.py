"""fedml_tpu: a TPU-native federated learning framework.

A ground-up JAX/XLA/pjit re-design of the capabilities of FedML
(arXiv:2007.13518; reference layout documented in SURVEY.md). Instead of
one-OS-process-per-client exchanging pickled state dicts over MPI, a federated
round here is a single SPMD program: per-client local training is vmapped (one
chip) or shard_mapped over a ``clients`` mesh axis (pod slice), and the
server's weighted average is an XLA ``psum`` riding the ICI.

Layers (mirroring reference layers, see SURVEY.md section 1):
  - ``fedml_tpu.core``       -- L0/L1: pytree math, message/control plane,
                                 partitioners, topology, robustness, trainer seam.
  - ``fedml_tpu.models``     -- L2a: Flax model zoo.
  - ``fedml_tpu.data``       -- L2b: federated dataset loaders (8-tuple contract).
  - ``fedml_tpu.algorithms`` -- L3: FL algorithms on the common round engine.
  - ``fedml_tpu.parallel``   -- mesh construction + the SPMD round engine.
  - ``fedml_tpu.experiments``-- L4: argparse-compatible entry points.
  - ``fedml_tpu.observability`` -- fedtrace: round tracing, metrics
                                 registry, control-plane flight recorder.
"""

__version__ = "0.1.0"
