"""Host-side wire compressors: the distributed uplink's byte diet.

The jit compressors (:mod:`.compressors`) run inside the simulated round
on device; this module is their host twin for the REAL wire -- pure
numpy, importable without jax (the soak swarm and the transports must
stay jax-free), and free to exploit what the binary codec can frame that
device storage cannot: sub-byte code packing. Both lowerings are named
by the round program's codec leg (``fedml_tpu.program.codec.CodecSpec``
resolves ``.device()``/``.host()`` from one spec string), and the
twin pair is drift-gated: ``tests/test_wire_drift.py`` fuzzes every
spec in ``wire_codecs()`` and pins the deterministic surfaces
byte-equal across the pair.

A compressed report replaces the ``params`` payload with

    cdelta      encoded pytree of the client's EF-compressed update delta
    compressor  the spec string the client encoded with

and keeps ``round`` as the delta's BASE reference: the delta is relative
to the model the client trained on, so the server reconstructs against
the params it issued at that round/version. Error feedback follows
DGC/EF-SignSGD for the BIASED compressors (topk, signsgd): the client
compresses ``delta + residual`` and keeps ``residual' = input -
decoded``, with the residual keyed by the client's STABLE rank id -- one
accumulator per client across every round it reports into. qsgd is
UNBIASED stochastic rounding and deliberately runs WITHOUT feedback
(``HostQSGD.ef = False``): composing EF with a wide-cell unbiased
quantizer is an amplifier, not a corrector -- the residual absorbs
per-entry noise of magnitude ~``scale = max|x|``, which inflates the
next round's scale, which inflates the noise; measured on the ternary
wire spec, the closed loop's residual grows EXPONENTIALLY (pinned in
``TestWireCompressors::test_qsgd_closed_loop_is_stable``'s with-feedback
counterexample). Unbiased quantizers converge by averaging (the QSGD
argument); feedback is what makes biased contractions converge.

Encoded leaf schemas (all values numpy; ``shape``/``dtype`` ride the
frame's JSON header as plain scalars):

- qsgd:    ``{"qp": uint8 bit-packed codes, "scale": f32[], "bits": B,
             "shape": [...], "dtype": name}`` -- codes are stochastic
  uniform quantization to ``2^(B-1)-1`` signed levels, packed at B bits
  per element. On the wire, ``bits`` finally buys bytes (the device
  codec stores int8 regardless -- its documented tradeoff), so the bare
  ``qsgd`` spec here defaults to B=2: ternary codes + per-leaf fp32
  scale + error feedback (the TernGrad regime), 16x smaller than fp32.
- topk:    ``{"values": f32[k], "indices": int32[k] (sorted), "shape",
             "dtype"}`` -- magnitude top-k, k = ceil(ratio * size).
- signsgd: ``{"sign": bool[...], "scale": f32[], "dtype"}`` -- the codec
  bit-packs bool arrays, so signs cost 1 bit/element on the wire.

The server never densifies a topk report to O(model): the
:class:`CompressedUpdate` payload folds its decoded update INTO the
shared fp64 accumulator sparsely (O(k) per report), and the canonical
fold (:func:`~fedml_tpu.resilience.policy.fold_entries_fp64`) adds each
distinct BASE exactly once, scaled by its entries' weight sum. See
docs/COMPRESSION.md "Distributed wire path" for what the bitwise
contract means under lossy compression.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

#: report-message keys of the compressed schema (shared vocabulary for
#: the FSMs, the swarm, and the fedcheck FL128 payload-schema pass)
WIRE_DELTA_KEY = "cdelta"
WIRE_SPEC_KEY = "compressor"


def pack_codes(codes, bits: int) -> np.ndarray:
    """Signed codes in ``[-L, L]`` (``L = 2^(bits-1) - 1``) -> uint8
    array of ``ceil(n * bits / 8)`` bytes (offset-binary, big-endian bit
    order). ``bits == 8`` passes through as the two's-complement byte.

    The even widths (2/4 bits: 4 or 2 codes per byte) pack by shifts
    over the flat uint8 array -- the swarm encodes thousands of reports
    per second on one core, and the generic ``unpackbits`` matrix walk
    was the measured encode hot spot (~10x slower). Odd widths keep the
    generic path; both produce identical bytes (fuzz-pinned)."""
    codes = np.asarray(codes)
    if bits == 8:
        return codes.astype(np.int8).view(np.uint8).reshape(-1)
    levels = 2 ** (bits - 1) - 1
    u = (codes.reshape(-1).astype(np.int16) + levels).astype(np.uint8)
    if bits in (2, 4):
        per = 8 // bits
        pad = (-len(u)) % per
        if pad:
            u = np.concatenate([u, np.zeros(pad, np.uint8)])
        m = u.reshape(-1, per)
        out = np.zeros(len(m), np.uint8)
        for j in range(per):  # big-endian bit order, MSB field first
            out |= m[:, j] << (8 - bits * (j + 1))
        return out
    bitmat = np.unpackbits(u[:, None], axis=1)[:, 8 - bits:]
    return np.packbits(bitmat.reshape(-1))


def unpack_codes(packed, n: int, bits: int) -> np.ndarray:
    """Inverse of :func:`pack_codes`: first ``n`` codes as int8."""
    packed = np.asarray(packed, np.uint8)
    if bits == 8:
        return packed.view(np.int8)[:n].copy()
    levels = 2 ** (bits - 1) - 1
    if bits in (2, 4):
        per = 8 // bits
        mask = (1 << bits) - 1
        shifts = [8 - bits * (j + 1) for j in range(per)]
        m = np.empty((len(packed), per), np.uint8)
        for j, s in enumerate(shifts):
            m[:, j] = (packed >> s) & mask
        u = m.reshape(-1)[:n]
        return (u.astype(np.int16) - levels).astype(np.int8)
    bitmat = np.unpackbits(packed, count=n * bits).reshape(n, bits)
    weights = (1 << np.arange(bits - 1, -1, -1)).astype(np.uint8)
    u = bitmat.astype(np.int16) @ weights.astype(np.int16)
    return (u - levels).astype(np.int8)


def packed_nbytes(size: int, bits: int) -> int:
    return (size * bits + 7) // 8


class HostCompressor:
    """Per-leaf numpy ``encode``/``decode`` lifted over flat param dicts
    (the control plane's payloads are ``{name: ndarray}``; nested
    pytrees are not needed on this path)."""

    name = "none"
    spec = "none"
    #: whether :func:`ef_step` accumulates an error-feedback residual
    #: through this compressor. True for biased contractions (topk,
    #: signsgd -- EF is what makes them converge); False for unbiased
    #: quantizers (qsgd -- feedback amplifies their variance into an
    #: exponentially growing residual, see the module docstring).
    ef = True

    def encode_leaf(self, x, rng):  # pragma: no cover - interface
        raise NotImplementedError

    def decode_leaf(self, enc):  # pragma: no cover - interface
        raise NotImplementedError

    def fold_leaf(self, acc, enc, scale: float):
        """Accumulate ``scale * float64(decode_leaf(enc))`` into the f64
        array ``acc`` in place. Subclasses override where the decoded
        form is sparse (topk: O(k), never densified)."""
        acc += float(scale) * self.decode_leaf(enc).astype(np.float64)

    def encode(self, tree, rng):
        return {k: self.encode_leaf(np.asarray(tree[k], np.float32), rng)
                for k in sorted(tree)}

    def decode(self, enc_tree):
        return {k: self.decode_leaf(enc_tree[k]) for k in sorted(enc_tree)}

    def __repr__(self):
        return f"{type(self).__name__}({self.spec!r})"


class HostQSGD(HostCompressor):
    """Stochastic uniform quantization, bit-packed at the code width.

    ``bits`` in [2, 8]; levels = ``2^(bits-1) - 1``. Unlike the device
    compressor (int8 storage either way), the wire packs codes at
    exactly ``bits`` bits per element, so the bare ``qsgd`` wire spec
    defaults to 2 -- ternary {-1, 0, +1} codes (the TernGrad regime).
    Unbiased by stochastic rounding, so it runs WITHOUT error feedback
    (``ef = False``; see the module docstring for the measured
    instability feedback causes here)."""

    name = "qsgd"
    ef = False

    def __init__(self, bits=2):
        if not 2 <= int(bits) <= 8:
            raise ValueError(f"qsgd bits must be in [2, 8], got {bits}")
        self.bits = int(bits)
        self.levels = 2 ** (self.bits - 1) - 1
        self.spec = f"qsgd:{self.bits}"

    def encode_leaf(self, x, rng):
        scale = float(np.max(np.abs(x))) if x.size else 0.0
        safe = max(scale, 1e-30)
        # f32 throughout: the quantizer's correctness is its value range
        # (stochastic rounding stays unbiased given the scale), and the
        # f64 walk doubled the swarm's per-report encode cost
        y = x.astype(np.float32) * np.float32(self.levels / safe)
        noise = rng.random(x.shape, dtype=np.float32)
        q = np.clip(np.floor(y + noise),
                    -self.levels, self.levels).astype(np.int8)
        return {"qp": pack_codes(q, self.bits),
                "scale": np.float32(scale), "bits": self.bits,
                "shape": [int(d) for d in x.shape], "dtype": str(x.dtype)}

    def decode_leaf(self, enc):
        shape = tuple(enc["shape"])
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        bits = int(enc["bits"])
        levels = 2 ** (bits - 1) - 1
        q = unpack_codes(np.asarray(enc["qp"]), size, bits)
        y = q.astype(np.float32) * (np.float32(enc["scale"])
                                    / np.float32(levels))
        return y.reshape(shape).astype(enc["dtype"])


class HostTopK(HostCompressor):
    """Magnitude top-k sparsification; indices sorted ascending (one
    canonical encoded form, and the sparse fold walks memory in order)."""

    name = "topk"

    def __init__(self, ratio=0.01):
        if not 0 < ratio <= 1:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        self.ratio = float(ratio)
        self.spec = f"topk:{self.ratio}"

    def encode_leaf(self, x, rng):
        del rng
        flat = x.reshape(-1)
        k = max(1, int(math.ceil(self.ratio * max(flat.size, 1))))
        if k >= flat.size:
            idx = np.arange(flat.size, dtype=np.int32)
        else:
            idx = np.sort(np.argpartition(np.abs(flat), -k)[-k:]
                          ).astype(np.int32)
        return {"values": flat[idx].astype(np.float32), "indices": idx,
                "shape": [int(d) for d in x.shape], "dtype": str(x.dtype)}

    def decode_leaf(self, enc):
        shape = tuple(enc["shape"])
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        flat = np.zeros(size, enc["dtype"])
        flat[np.asarray(enc["indices"])] = np.asarray(
            enc["values"]).astype(enc["dtype"])
        return flat.reshape(shape)

    def fold_leaf(self, acc, enc, scale: float):
        # O(k): only the kept coordinates touch the accumulator -- the
        # decoded update is zeros elsewhere, so this IS
        # scale * f64(decode), never densified per report
        vals = np.asarray(enc["values"]).astype(
            enc["dtype"]).astype(np.float64)
        np.add.at(acc.reshape(-1), np.asarray(enc["indices"]),
                  float(scale) * vals)


class HostSignSGD(HostCompressor):
    """1-bit sign + per-leaf mean-|x| magnitude; the codec bit-packs the
    bool sign array to 1 bit/element on the wire."""

    name = "signsgd"
    spec = "signsgd"

    def encode_leaf(self, x, rng):
        del rng
        return {"sign": x >= 0,
                "scale": np.float32(np.mean(np.abs(x)) if x.size else 0.0),
                "dtype": str(x.dtype)}

    def decode_leaf(self, enc):
        sign = np.asarray(enc["sign"])
        scale = np.float32(enc["scale"])
        return np.where(sign, scale, -scale).astype(enc["dtype"])


_HOST_REGISTRY = {"qsgd": HostQSGD, "topk": HostTopK,
                  "signsgd": HostSignSGD}


def host_compressor(spec):
    """Spec string -> host compressor (``None``/``none``/empty -> None:
    the driver keeps today's plain-``params`` path, bitwise-identical to
    before -- there is no identity wire transform, by design).

    Grammar matches :func:`.compressors.get_compressor` (``qsgd:4``,
    ``topk:0.01``, ``signsgd``) with one documented divergence: bare
    ``qsgd`` defaults to 2 bits here (the wire packs sub-byte codes, so
    narrow widths finally buy bytes) while the device compressor's
    int8-storage default stays 8."""
    if spec is None or isinstance(spec, HostCompressor):
        return spec
    s = str(spec).strip().lower()
    if not s or s in ("0", "off", "false", "none"):
        return None
    name, _, arg = s.partition(":")
    if name == "randk":
        raise ValueError("randk is a sim-only compressor (unbiased "
                         "sparsification needs the shared rng stream); "
                         "use topk on the wire")
    if name not in _HOST_REGISTRY:
        raise ValueError(f"unknown wire compressor {name!r} "
                         f"(known: {sorted(_HOST_REGISTRY)})")
    cls = _HOST_REGISTRY[name]
    if not arg:
        return cls()
    if name == "topk":
        return cls(ratio=float(arg))
    if name == "qsgd":
        return cls(bits=int(arg))
    raise ValueError(f"wire compressor {name!r} takes no argument "
                     f"(got {arg!r})")


def encode_rng(seed_tuple) -> np.random.Generator:
    """The one seeded stream rule for wire encodes: keyed (never
    sequential) on ``(rank, round/version, attempt)`` so two runs over
    the same schedule encode bit-identically regardless of thread
    timing."""
    return np.random.default_rng((0x5EED, *map(int, seed_tuple)))


def ef_step(compressor: HostCompressor, delta, residual, rng):
    """One uplink compression step over flat param dicts (numpy). For
    EF compressors (``compressor.ef``, the biased contractions):
    ``enc = encode(delta + residual)``, ``decoded`` is the server's view,
    ``residual' = (delta + residual) - decoded``; ``residual`` of None
    means a zero accumulator (first report of this client). For unbiased
    compressors (qsgd): ``enc = encode(delta)`` and the returned residual
    is always None -- feedback deliberately off (module docstring)."""
    if not compressor.ef:
        enc = compressor.encode(
            {k: np.asarray(delta[k], np.float32) for k in sorted(delta)},
            rng)
        return enc, compressor.decode(enc), None
    comp_in = {k: np.asarray(delta[k], np.float32)
               + (np.float32(0) if residual is None
                  else residual[k]) for k in sorted(delta)}
    enc = compressor.encode(comp_in, rng)
    decoded = compressor.decode(enc)
    new_residual = {k: comp_in[k] - decoded[k] for k in comp_in}
    return enc, decoded, new_residual


@dataclass(frozen=True)
class CompressedUpdate:
    """A compressed report's payload as the fold sees it: the encoded
    delta plus the BASE params it is relative to (resolved by the server
    from the round/version the client reported against).

    :func:`~fedml_tpu.resilience.policy.fold_entries_fp64` folds these
    without densifying: each entry contributes
    ``scale * float64(decode(enc))`` into the shared f64 accumulator
    (O(k) for topk), and each DISTINCT base contributes
    ``(sum of its entries' scales) * float64(base)`` exactly once, in
    sorted ``base_key`` order -- so the fold stays sorted-key
    deterministic and the async oracle (decay 0, one shared base per
    window) still equals the synchronous fold bitwise.
    """

    enc: dict
    spec: str
    base: dict
    base_key: int = 0
    _comp: HostCompressor = field(default=None, compare=False, repr=False)

    def compressor(self) -> HostCompressor:
        c = self._comp or host_compressor(self.spec)
        if c is None:
            raise ValueError(f"CompressedUpdate with a plain spec "
                             f"{self.spec!r}")
        return c

    def fold_delta(self, acc, scale: float):
        """Accumulate this entry's decoded-delta contribution into
        ``acc`` (``{name: float64 ndarray}``; None allocates zeros from
        the base's shapes) and return it."""
        if acc is None:
            acc = {k: np.zeros(np.shape(self.base[k]), np.float64)
                   for k in sorted(self.base)}
        comp = self.compressor()
        for k in sorted(self.enc):
            comp.fold_leaf(acc[k], self.enc[k], scale)
        return acc


def wire_payload_nbytes(compressor, template) -> int:
    """Exact on-wire bytes of one compressed report's ``cdelta`` section
    through the binary codec, computed from the template's shapes alone
    (encode a zero update -- sizes are shape-static). The uncompressed
    floor is :func:`tree_wire_nbytes` of the raw template."""
    from fedml_tpu.compression.codec import tree_wire_nbytes

    zeros = {k: np.zeros(np.shape(v), np.float32)
             for k, v in template.items()}
    enc = compressor.encode(zeros, encode_rng((0, 0, 0)))
    return tree_wire_nbytes(enc)


__all__ = ["WIRE_DELTA_KEY", "WIRE_SPEC_KEY", "HostCompressor", "HostQSGD",
           "HostTopK", "HostSignSGD", "host_compressor", "encode_rng",
           "ef_step", "CompressedUpdate", "pack_codes", "unpack_codes",
           "packed_nbytes", "wire_payload_nbytes"]
