"""``fedml_tpu.compression``: client-update compression + binary wire codec.

Two layers, composable and separately usable:

- :mod:`~fedml_tpu.compression.codec` -- binary framing for ndarray
  payloads on the control-plane transports (header + dtype + shape + raw
  bytes; JSON stays for scalar control fields; version byte for
  back-compat). Numpy-only: importable without jax.
- :mod:`~fedml_tpu.compression.compressors` -- jit-compatible pytree
  compressors (``none``/``topk``/``randk``/``qsgd``/``signsgd``) with
  :class:`ErrorFeedback` residual accumulation, selected by spec string
  via :func:`get_compressor` (``--compressor qsgd:8``).
- :mod:`~fedml_tpu.compression.integration` -- the compressed FedAvg-family
  round (error feedback carried per client across rounds) and on-wire byte
  accounting behind the per-round ``bytes_on_wire`` /
  ``compression_ratio`` metrics fields.
- :mod:`~fedml_tpu.compression.wire` -- host (numpy-only) compressors for
  the DISTRIBUTED uplink: clients ship EF-compressed update deltas
  (``cdelta`` + ``compressor`` report keys) and the servers fold them
  sparsely/quantized through the canonical fp64 fold without densifying
  per report. Importable without jax (the soak swarm's path).

Exports resolve lazily so that importing :mod:`.codec` (directly or from
the transports) never drags in jax via this package ``__init__`` --
compressors/integration load on first attribute access.

See ``docs/COMPRESSION.md`` for the wire format and measured sizes.
"""

_EXPORTS = {
    "fedml_tpu.compression.codec": (
        "encode_array", "decode_array", "encode_tree", "decode_tree",
        "message_to_wire", "message_from_wire", "tree_wire_nbytes"),
    "fedml_tpu.compression.compressors": (
        "Compressor", "NoneCompressor", "TopKCompressor", "RandKCompressor",
        "QSGDCompressor", "SignSGDCompressor", "ErrorFeedback",
        "get_compressor"),
    "fedml_tpu.compression.integration": (
        "make_compressed_sim_round", "ResidualStore",
        "compressed_payload_nbytes", "raw_payload_nbytes"),
    "fedml_tpu.compression.wire": (
        "host_compressor", "HostCompressor", "CompressedUpdate",
        "ef_step", "encode_rng", "wire_payload_nbytes",
        "WIRE_DELTA_KEY", "WIRE_SPEC_KEY"),
}

__all__ = [name for names in _EXPORTS.values() for name in names]

_BY_NAME = {name: mod for mod, names in _EXPORTS.items() for name in names}


def __getattr__(name):
    mod = _BY_NAME.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(__all__))
