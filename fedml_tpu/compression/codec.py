"""Binary wire codec for ndarray payloads: header + dtype + shape + raw bytes.

The control-plane transports inherited the reference's mobile codec --
``Message.to_json`` turns every ndarray into JSON nested lists
(``fedml_api/distributed/fedavg/utils.py:5-14``), which costs ~12-18 text
bytes per fp32 element plus Python-level encode/decode. This module frames
arrays as raw bytes instead (npz-style: self-describing header, then the
buffer), with JSON retained for scalar control fields and a version byte so
transports can keep decoding legacy all-JSON frames.

Wire format (all integers big-endian):

  message frame    = MAGIC(0x9E) VERSION(0x01) hdr_len:u32 hdr_json arrays*
  hdr_json         = msg_params with every ndarray leaf replaced by
                     {"__nd__": i} (i = position in the arrays section)
  array frame      = name_len:u8 dtype_name ndim:u8 (dim:u32)*ndim
                     nbytes:u32 payload
  payload          = C-order little-endian raw bytes; bool arrays are
                     bit-packed (np.packbits -- 1 bit/element on the wire)

0x9E cannot start a JSON document, so ``message_from_wire`` dispatches on
the first byte: legacy peers sending ``Message.to_json()`` frames keep
working, and a future VERSION bump is a one-byte sniff away. No pickle
anywhere -- the payload is data, never code.

This module deliberately imports only numpy (+ ml_dtypes for bfloat16 when
present): the TCP transport must stay importable without pulling in jax.
"""

from __future__ import annotations

import json
import struct
import sys

import numpy as np

MAGIC = 0x9E
VERSION = 1
_HDR_LEN = struct.Struct("!I")
_DIM = struct.Struct("!I")
_ND_KEY = "__nd__"

try:  # bfloat16 is a first-class wire dtype when ml_dtypes is present
    import ml_dtypes
    _EXTRA_DTYPES = {"bfloat16": np.dtype(ml_dtypes.bfloat16)}
except ImportError:  # pragma: no cover - baked image ships ml_dtypes
    _EXTRA_DTYPES = {}


def _resolve_dtype(name: str) -> np.dtype:
    if name in _EXTRA_DTYPES:
        return _EXTRA_DTYPES[name]
    try:
        return np.dtype(name)
    except TypeError:
        raise ValueError(f"codec: unknown wire dtype {name!r}") from None


def _as_host_array(x) -> np.ndarray:
    """Any array-ish (numpy, jax, memoryview) -> contiguous host ndarray."""
    a = np.asarray(x)
    if a.dtype == object:
        raise TypeError("codec: object arrays are not wire-serializable")
    # ascontiguousarray promotes 0-d to 1-d; 0-d is always contiguous
    return a if a.flags.c_contiguous else np.ascontiguousarray(a)


def array_wire_nbytes(shape, dtype) -> int:
    """Exact on-wire size of one array frame (header + payload)."""
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    name = dt.name.encode("ascii")
    size = int(np.prod(shape, dtype=np.int64)) if len(shape) else 1
    if dt == np.bool_:
        payload = (size + 7) // 8
    else:
        payload = size * dt.itemsize
    return 1 + len(name) + 1 + _DIM.size * len(shape) + _DIM.size + payload


def encode_array_views(x) -> list:
    """Zero-copy array frame as ``[header_bytes, payload_buffer]``.

    The payload buffer is a read-only ``memoryview`` over the array's own
    memory whenever the in-memory layout already matches the wire
    (C-contiguous, little-endian, non-bool) -- the hot encode path never
    copies the tensor bytes; transports with scatter-gather writes (the
    event-loop write queue, ``fedml_tpu.net.eventloop``) send the views
    directly and :func:`encode_tree` joins them exactly once. Layouts the
    wire cannot alias (bool bit-packing, byte-swaps, non-contiguous
    inputs) degrade to the inherent one conversion copy. NOTE: a view
    aliases the caller's array until the bytes are written -- senders must
    not mutate a payload between enqueue and flush (the FSMs build fresh
    report/sync payloads per send, so this holds by construction).
    """
    a = _as_host_array(x)
    # wire is little-endian: swap explicit-BE arrays, and native arrays
    # when the host itself is big-endian
    if a.dtype.itemsize > 1 and (
            a.dtype.byteorder == ">"
            or (a.dtype.byteorder == "=" and sys.byteorder == "big")):
        a = a.byteswap().view(a.dtype.newbyteorder("<"))
    name = a.dtype.name.encode("ascii")
    if a.dtype == np.bool_:
        payload = np.packbits(a.reshape(-1)).data.cast("B")
    else:
        try:
            payload = a.data.cast("B")  # zero-copy: aliases the array
        except (ValueError, TypeError, BufferError):
            payload = a.tobytes()  # exotic layout: pure-Python fallback
    parts = [struct.pack("!B", len(name)), name,
             struct.pack("!B", a.ndim)]
    parts += [_DIM.pack(d) for d in a.shape]
    parts.append(_DIM.pack(len(payload)))
    return [b"".join(parts), payload]


def encode_array(x) -> bytes:
    return b"".join(encode_array_views(x))


def decode_array(buf: bytes, offset: int = 0):
    """Decode one array frame at ``offset``; returns ``(array, new_offset)``."""
    (nlen,) = struct.unpack_from("!B", buf, offset)
    offset += 1
    name = buf[offset:offset + nlen].decode("ascii")
    offset += nlen
    (ndim,) = struct.unpack_from("!B", buf, offset)
    offset += 1
    shape = []
    for _ in range(ndim):
        (d,) = _DIM.unpack_from(buf, offset)
        shape.append(d)
        offset += _DIM.size
    (nbytes,) = _DIM.unpack_from(buf, offset)
    offset += _DIM.size
    payload = buf[offset:offset + nbytes]
    if len(payload) != nbytes:
        raise ValueError("codec: truncated array payload")
    offset += nbytes
    dt = _resolve_dtype(name)
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if dt == np.bool_:
        bits = np.unpackbits(np.frombuffer(payload, np.uint8), count=size)
        arr = bits.astype(np.bool_).reshape(shape)
    else:
        arr = np.frombuffer(payload, dt)
        if sys.byteorder == "big" and dt.itemsize > 1:
            arr = arr.byteswap()  # wire is little-endian, host is not
        arr = arr.reshape(shape)
    return arr, offset


def _is_array(v) -> bool:
    """Anything with a dtype+shape goes binary, including 0-d arrays (a
    framed 0-d leaf keeps its exact dtype -- e.g. a bf16 quantizer scale --
    where ``.item()`` would launder it through a Python float). Plain
    Python scalars and numpy *scalar types* (``np.float32(x)``) stay JSON:
    control fields remain human-greppable."""
    if isinstance(v, (str, bytes, np.generic)):
        return False
    if isinstance(v, np.ndarray):
        return True
    # jax arrays (and other duck-typed ndarrays) without importing jax
    return (hasattr(v, "__array__") and hasattr(v, "dtype")
            and hasattr(v, "shape"))


def _extract(value, arrays: list):
    """Structure walk: replace every ndarray leaf with a {"__nd__": i}
    marker, collecting the arrays in order. Dicts/lists/tuples recurse;
    numpy scalars degrade to Python scalars (JSON)."""
    if _is_array(value):
        arrays.append(_as_host_array(value))
        return {_ND_KEY: len(arrays) - 1}
    if isinstance(value, dict):
        if _ND_KEY in value:
            raise ValueError(f"codec: payload dict key {_ND_KEY!r} is "
                             "reserved for array markers")
        return {k: _extract(v, arrays) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_extract(v, arrays) for v in value]
    if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
        return value.item()
    return value


def _restore(value, arrays: list):
    if isinstance(value, dict):
        if set(value.keys()) == {_ND_KEY}:
            return arrays[value[_ND_KEY]]
        return {k: _restore(v, arrays) for k, v in value.items()}
    if isinstance(value, list):
        return [_restore(v, arrays) for v in value]
    return value


def encode_tree_views(tree) -> list:
    """Pytree -> list of wire buffers (bytes/memoryviews) whose
    concatenation is exactly :func:`encode_tree`'s output. Array payloads
    stay zero-copy views over the caller's arrays (see
    :func:`encode_array_views`); a vectored-write transport sends the
    list as-is and skips frame assembly entirely."""
    arrays: list = []
    header = json.dumps(_extract(tree, arrays)).encode()
    views = [bytes((MAGIC, VERSION)) + _HDR_LEN.pack(len(header)) + header]
    for a in arrays:
        views.extend(encode_array_views(a))
    return views


def encode_tree(tree) -> bytes:
    """Pytree (nested dict/list/tuple of arrays + scalars) -> wire bytes.
    One join over the zero-copy views: each tensor's bytes are copied
    exactly once, into the final frame (the old per-array ``tobytes`` +
    per-frame join copied every payload twice)."""
    return b"".join(encode_tree_views(tree))


def decode_tree(data: bytes):
    """Inverse of :func:`encode_tree`."""
    if len(data) < 2 or data[0] != MAGIC:
        raise ValueError("codec: not a binary tree frame")
    if data[1] != VERSION:
        raise ValueError(f"codec: unsupported wire version {data[1]}")
    (hlen,) = _HDR_LEN.unpack_from(data, 2)
    off = 2 + _HDR_LEN.size
    header = json.loads(data[off:off + hlen].decode())
    off += hlen
    arrays = []
    while off < len(data):
        arr, off = decode_array(data, off)
        arrays.append(arr)
    return _restore(header, arrays)


def tree_wire_nbytes(tree) -> int:
    """On-wire size of :func:`encode_tree` WITHOUT materializing the bytes.
    Accepts concrete arrays or anything with ``.shape``/``.dtype`` (e.g.
    ``jax.eval_shape`` structs), so compressed-payload sizes can be computed
    once from abstract shapes at API-init time."""
    arrays: list = []

    def walk(v):
        # same array predicate as encode_tree, plus shape/dtype ducks with
        # no __array__ (jax.eval_shape ShapeDtypeStructs)
        if _is_array(v) or (hasattr(v, "shape") and hasattr(v, "dtype")
                            and not isinstance(v, (str, bytes, np.generic))):
            arrays.append(v)
            return {_ND_KEY: len(arrays) - 1}
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [walk(x) for x in v]
        if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
            return v.item()
        return v

    header = json.dumps(walk(tree)).encode()
    n = 2 + _HDR_LEN.size + len(header)
    for a in arrays:
        n += array_wire_nbytes(tuple(a.shape), np.dtype(a.dtype))
    return n


# -- Message envelope ---------------------------------------------------------

def message_to_wire(msg) -> bytes:
    """``Message`` -> binary frame: JSON control header, binary arrays."""
    return encode_tree(msg.get_params())


def message_to_wire_views(msg) -> list:
    """``Message`` -> list of wire buffers (zero-copy array payloads);
    ``b"".join(...)`` of the list equals :func:`message_to_wire`."""
    return encode_tree_views(msg.get_params())


def message_from_wire(data: bytes):
    """Binary OR legacy-JSON frame -> ``Message`` (first-byte sniff: 0x9E
    is the binary magic and cannot start a JSON document)."""
    from fedml_tpu.core.message import Message
    msg = Message()
    if data[:1] == bytes((MAGIC,)):
        params = decode_tree(data)
        msg.init(params)
        msg.type = str(params[Message.MSG_ARG_KEY_TYPE])
        msg.sender_id = params[Message.MSG_ARG_KEY_SENDER]
        msg.receiver_id = params[Message.MSG_ARG_KEY_RECEIVER]
        return msg
    msg.init_from_json_string(
        data.decode() if isinstance(data, (bytes, bytearray)) else data)
    return msg


__all__ = ["MAGIC", "VERSION", "encode_array", "encode_array_views",
           "decode_array", "encode_tree", "encode_tree_views",
           "decode_tree", "array_wire_nbytes", "tree_wire_nbytes",
           "message_to_wire", "message_to_wire_views",
           "message_from_wire"]
