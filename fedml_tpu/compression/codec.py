"""Binary wire codec for ndarray payloads: header + dtype + shape + raw bytes.

The control-plane transports inherited the reference's mobile codec --
``Message.to_json`` turns every ndarray into JSON nested lists
(``fedml_api/distributed/fedavg/utils.py:5-14``), which costs ~12-18 text
bytes per fp32 element plus Python-level encode/decode. This module frames
arrays as raw bytes instead (npz-style: self-describing header, then the
buffer), with JSON retained for scalar control fields and a version byte so
transports can keep decoding legacy all-JSON frames.

Wire format (all integers big-endian):

  message frame    = MAGIC(0x9E) VERSION(0x01) hdr_len:u32 hdr_json arrays*
  hdr_json         = msg_params with every ndarray leaf replaced by
                     {"__nd__": i} (i = position in the arrays section)
  array frame      = name_len:u8 dtype_name ndim:u8 (dim:u32)*ndim
                     nbytes:u32 payload
  payload          = C-order little-endian raw bytes; bool arrays are
                     bit-packed (np.packbits -- 1 bit/element on the wire)

0x9E cannot start a JSON document, so ``message_from_wire`` dispatches on
the first byte: legacy peers sending ``Message.to_json()`` frames keep
working, and a future VERSION bump is a one-byte sniff away. No pickle
anywhere -- the payload is data, never code.

This module deliberately imports only numpy (+ ml_dtypes for bfloat16 when
present): the TCP transport must stay importable without pulling in jax.
"""

from __future__ import annotations

import json
import struct
import sys

import numpy as np

MAGIC = 0x9E
VERSION = 1
_HDR_LEN = struct.Struct("!I")
_DIM = struct.Struct("!I")
_ND_KEY = "__nd__"

try:  # bfloat16 is a first-class wire dtype when ml_dtypes is present
    import ml_dtypes
    _EXTRA_DTYPES = {"bfloat16": np.dtype(ml_dtypes.bfloat16)}
except ImportError:  # pragma: no cover - baked image ships ml_dtypes
    _EXTRA_DTYPES = {}


def _resolve_dtype(name: str) -> np.dtype:
    if name in _EXTRA_DTYPES:
        return _EXTRA_DTYPES[name]
    try:
        return np.dtype(name)
    except TypeError:
        raise ValueError(f"codec: unknown wire dtype {name!r}") from None


def _as_host_array(x) -> np.ndarray:
    """Any array-ish (numpy, jax, memoryview) -> contiguous host ndarray."""
    a = np.asarray(x)
    if a.dtype == object:
        raise TypeError("codec: object arrays are not wire-serializable")
    # ascontiguousarray promotes 0-d to 1-d; 0-d is always contiguous
    return a if a.flags.c_contiguous else np.ascontiguousarray(a)


def array_wire_nbytes(shape, dtype) -> int:
    """Exact on-wire size of one array frame (header + payload)."""
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    name = dt.name.encode("ascii")
    size = int(np.prod(shape, dtype=np.int64)) if len(shape) else 1
    if dt == np.bool_:
        payload = (size + 7) // 8
    else:
        payload = size * dt.itemsize
    return 1 + len(name) + 1 + _DIM.size * len(shape) + _DIM.size + payload


def encode_array_views(x) -> list:
    """Zero-copy array frame as ``[header_bytes, payload_buffer]``.

    The payload buffer is a read-only ``memoryview`` over the array's own
    memory whenever the in-memory layout already matches the wire
    (C-contiguous, little-endian, non-bool) -- the hot encode path never
    copies the tensor bytes; transports with scatter-gather writes (the
    event-loop write queue, ``fedml_tpu.net.eventloop``) send the views
    directly and :func:`encode_tree` joins them exactly once. Layouts the
    wire cannot alias (bool bit-packing, byte-swaps, non-contiguous
    inputs) degrade to the inherent one conversion copy. NOTE: a view
    aliases the caller's array until the bytes are written -- senders must
    not mutate a payload between enqueue and flush (the FSMs build fresh
    report/sync payloads per send, so this holds by construction).
    """
    a = _as_host_array(x)
    # wire is little-endian: swap explicit-BE arrays, and native arrays
    # when the host itself is big-endian
    if a.dtype.itemsize > 1 and (
            a.dtype.byteorder == ">"
            or (a.dtype.byteorder == "=" and sys.byteorder == "big")):
        a = a.byteswap().view(a.dtype.newbyteorder("<"))
    name = a.dtype.name.encode("ascii")
    if a.dtype == np.bool_:
        payload = np.packbits(a.reshape(-1)).data.cast("B")
    else:
        try:
            payload = a.data.cast("B")  # zero-copy: aliases the array
        except (ValueError, TypeError, BufferError):
            payload = a.tobytes()  # exotic layout: pure-Python fallback
    parts = [struct.pack("!B", len(name)), name,
             struct.pack("!B", a.ndim)]
    parts += [_DIM.pack(d) for d in a.shape]
    parts.append(_DIM.pack(len(payload)))
    return [b"".join(parts), payload]


def encode_array(x) -> bytes:
    return b"".join(encode_array_views(x))


def decode_array(buf, offset: int = 0):
    """Decode one array frame at ``offset``; returns ``(array, new_offset)``.

    ``buf`` may be ``bytes``, ``bytearray``, or a ``memoryview`` over the
    transport's receive buffer. The decode twin of
    :func:`encode_array_views`: a contiguous little-endian payload of a
    native dtype is returned as an ``np.frombuffer`` view that ALIASES
    ``buf`` -- zero payload copies from the wire to the aggregator fold
    (``np.shares_memory``-pinned in tests). The view is marked read-only
    when the backing buffer is mutable, and it keeps the buffer alive by
    reference: the retention contract is that a transport hands each
    frame buffer off whole and never writes into it again (the event
    loop allocates a fresh ``bytearray`` per frame). Layouts the wire
    cannot alias -- bool bit-packing, extension dtypes (bf16), and
    big-endian hosts -- fall back to the one-conversion copying path,
    byte-equal."""
    (nlen,) = struct.unpack_from("!B", buf, offset)
    offset += 1
    name = bytes(buf[offset:offset + nlen]).decode("ascii")
    offset += nlen
    (ndim,) = struct.unpack_from("!B", buf, offset)
    offset += 1
    shape = []
    for _ in range(ndim):
        (d,) = _DIM.unpack_from(buf, offset)
        shape.append(d)
        offset += _DIM.size
    (nbytes,) = _DIM.unpack_from(buf, offset)
    offset += _DIM.size
    if len(buf) - offset < nbytes:
        raise ValueError("codec: truncated array payload")
    dt = _resolve_dtype(name)
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if dt == np.bool_:
        bits = np.unpackbits(
            np.frombuffer(buf, np.uint8, count=nbytes, offset=offset),
            count=size)
        arr = bits.astype(np.bool_).reshape(shape)
    elif name in _EXTRA_DTYPES or (sys.byteorder == "big"
                                   and dt.itemsize > 1):
        # copying path: extension dtypes stay off the aliasing fast path
        # (conservative across numpy versions), and a big-endian host
        # must byteswap off the little-endian wire anyway
        arr = np.frombuffer(bytes(buf[offset:offset + nbytes]), dt)
        if sys.byteorder == "big" and dt.itemsize > 1:
            arr = arr.byteswap()
        arr = arr.reshape(shape)
    else:
        if nbytes != size * dt.itemsize:
            raise ValueError("codec: array payload size mismatch")
        arr = np.frombuffer(buf, dt, count=size, offset=offset)
        if arr.flags.writeable:
            # aliases a mutable receive buffer: freeze the view so no
            # consumer can corrupt a sibling array sharing the frame
            arr.flags.writeable = False
        arr = arr.reshape(shape)
    return arr, offset + nbytes


def _is_array(v) -> bool:
    """Anything with a dtype+shape goes binary, including 0-d arrays (a
    framed 0-d leaf keeps its exact dtype -- e.g. a bf16 quantizer scale --
    where ``.item()`` would launder it through a Python float). Plain
    Python scalars and numpy *scalar types* (``np.float32(x)``) stay JSON:
    control fields remain human-greppable."""
    if isinstance(v, (str, bytes, np.generic)):
        return False
    if isinstance(v, np.ndarray):
        return True
    # jax arrays (and other duck-typed ndarrays) without importing jax
    return (hasattr(v, "__array__") and hasattr(v, "dtype")
            and hasattr(v, "shape"))


def _extract(value, arrays: list):
    """Structure walk: replace every ndarray leaf with a {"__nd__": i}
    marker, collecting the arrays in order. Dicts/lists/tuples recurse;
    numpy scalars degrade to Python scalars (JSON)."""
    if _is_array(value):
        arrays.append(_as_host_array(value))
        return {_ND_KEY: len(arrays) - 1}
    if isinstance(value, dict):
        if _ND_KEY in value:
            raise ValueError(f"codec: payload dict key {_ND_KEY!r} is "
                             "reserved for array markers")
        return {k: _extract(v, arrays) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_extract(v, arrays) for v in value]
    if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
        return value.item()
    return value


def _restore(value, arrays: list):
    if isinstance(value, dict):
        if set(value.keys()) == {_ND_KEY}:
            return arrays[value[_ND_KEY]]
        return {k: _restore(v, arrays) for k, v in value.items()}
    if isinstance(value, list):
        return [_restore(v, arrays) for v in value]
    return value


def encode_tree_views(tree) -> list:
    """Pytree -> list of wire buffers (bytes/memoryviews) whose
    concatenation is exactly :func:`encode_tree`'s output. Array payloads
    stay zero-copy views over the caller's arrays (see
    :func:`encode_array_views`); a vectored-write transport sends the
    list as-is and skips frame assembly entirely."""
    arrays: list = []
    header = json.dumps(_extract(tree, arrays), sort_keys=True).encode()
    views = [bytes((MAGIC, VERSION)) + _HDR_LEN.pack(len(header)) + header]
    for a in arrays:
        views.extend(encode_array_views(a))
    return views


def encode_tree(tree) -> bytes:
    """Pytree (nested dict/list/tuple of arrays + scalars) -> wire bytes.
    One join over the zero-copy views: each tensor's bytes are copied
    exactly once, into the final frame (the old per-array ``tobytes`` +
    per-frame join copied every payload twice)."""
    return b"".join(encode_tree_views(tree))


def parse_wire_header(data):
    """Parse ONLY a binary frame's JSON control header: returns
    ``(header, offset)`` where ``header`` is the msg_params dict with
    ``{"__nd__": i}`` markers still in place and ``offset`` is where the
    array frames begin. This is the amortized half of a batched decode
    (one pass per chunk) and the whole decode a relay needs -- the hubs
    route on ``header["receiver"]`` and re-queue the RAW frame, so a
    relayed tensor payload is never decoded at all."""
    if len(data) < 2 or data[0] != MAGIC:
        raise ValueError("codec: not a binary tree frame")
    if data[1] != VERSION:
        raise ValueError(f"codec: unsupported wire version {data[1]}")
    (hlen,) = _HDR_LEN.unpack_from(data, 2)
    off = 2 + _HDR_LEN.size
    header = json.loads(bytes(data[off:off + hlen]).decode())
    return header, off + hlen


def decode_tree(data):
    """Inverse of :func:`encode_tree`; accepts ``bytes`` | ``bytearray``
    | ``memoryview`` (array payloads alias it -- see
    :func:`decode_array`)."""
    header, off = parse_wire_header(data)
    arrays = []
    while off < len(data):
        arr, off = decode_array(data, off)
        arrays.append(arr)
    return _restore(header, arrays)


def tree_wire_nbytes(tree) -> int:
    """On-wire size of :func:`encode_tree` WITHOUT materializing the bytes.
    Accepts concrete arrays or anything with ``.shape``/``.dtype`` (e.g.
    ``jax.eval_shape`` structs), so compressed-payload sizes can be computed
    once from abstract shapes at API-init time."""
    arrays: list = []

    def walk(v):
        # same array predicate as encode_tree, plus shape/dtype ducks with
        # no __array__ (jax.eval_shape ShapeDtypeStructs)
        if _is_array(v) or (hasattr(v, "shape") and hasattr(v, "dtype")
                            and not isinstance(v, (str, bytes, np.generic))):
            arrays.append(v)
            return {_ND_KEY: len(arrays) - 1}
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [walk(x) for x in v]
        if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
            return v.item()
        return v

    header = json.dumps(walk(tree), sort_keys=True).encode()
    n = 2 + _HDR_LEN.size + len(header)
    for a in arrays:
        n += array_wire_nbytes(tuple(a.shape), np.dtype(a.dtype))
    return n


# -- Message envelope ---------------------------------------------------------

def message_to_wire(msg) -> bytes:
    """``Message`` -> binary frame: JSON control header, binary arrays."""
    return encode_tree(msg.get_params())


def message_to_wire_views(msg) -> list:
    """``Message`` -> list of wire buffers (zero-copy array payloads);
    ``b"".join(...)`` of the list equals :func:`message_to_wire`."""
    return encode_tree_views(msg.get_params())


def _message_from_params(message_cls, params):
    msg = message_cls()
    msg.init(params)
    msg.type = str(params[message_cls.MSG_ARG_KEY_TYPE])
    msg.sender_id = params[message_cls.MSG_ARG_KEY_SENDER]
    msg.receiver_id = params[message_cls.MSG_ARG_KEY_RECEIVER]
    return msg


def _is_binary(data) -> bool:
    """First-byte sniff (0x9E cannot start a JSON document), for any
    bytes-like ``data``; str (legacy JSON text) is never binary."""
    if isinstance(data, str):
        return False
    return len(data) >= 1 and data[0] == MAGIC


def message_from_wire(data):
    """Binary OR legacy-JSON frame -> ``Message`` (first-byte sniff: 0x9E
    is the binary magic and cannot start a JSON document). Accepts
    ``bytes`` | ``bytearray`` | ``memoryview`` | ``str``; binary tensor
    payloads alias ``data`` (see :func:`decode_array`)."""
    from fedml_tpu.core.message import Message
    if _is_binary(data):
        return _message_from_params(Message, decode_tree(data))
    msg = Message()
    msg.init_from_json_string(
        data if isinstance(data, str) else bytes(data).decode())
    return msg


def message_from_header(header, data, offset):
    """Second half of a split decode: ``parse_wire_header`` gave
    ``(header, offset)``; this decodes the array frames from ``offset``
    and builds the ``Message`` -- the header JSON is parsed exactly
    once per frame even when the caller routed on it first."""
    from fedml_tpu.core.message import Message
    arrays = []
    off = offset
    while off < len(data):
        arr, off = decode_array(data, off)
        arrays.append(arr)
    return _message_from_params(Message, _restore(header, arrays))


def peek_wire_envelope(data):
    """``(type, sender, receiver)`` of a frame WITHOUT decoding any
    array payload: binary frames parse only the JSON control header;
    legacy JSON frames (tiny control messages) parse whole. The hubs'
    relay path routes on this and re-queues the raw frame -- the
    destination, not the relay, validates the payload."""
    from fedml_tpu.core.message import Message
    if _is_binary(data):
        header, _ = parse_wire_header(data)
    else:
        header = json.loads(
            data if isinstance(data, str) else bytes(data).decode())
    return (str(header[Message.MSG_ARG_KEY_TYPE]),
            header[Message.MSG_ARG_KEY_SENDER],
            header[Message.MSG_ARG_KEY_RECEIVER])


#: Exception types one undecodable frame can raise -- the concrete
#: failure set the transports catch (a malformed peer must cost one
#: connection, never the decode stage or a serve thread).
DECODE_ERRORS = (ValueError, KeyError, IndexError, TypeError,
                 struct.error, UnicodeDecodeError)


def decode_frames(frames):
    """Batch decode: one pass over a chunk of wire frames -> a list
    aligned with ``frames`` holding ``Message`` objects, with
    undecodable frames carried as their exception instance (the caller
    decides the peer's fate; one bad frame must not poison the chunk).
    Amortizes the per-frame import/dispatch overhead the event-loop
    dispatcher used to pay once per frame, and every tensor payload
    aliases its frame buffer (zero-copy decode)."""
    from fedml_tpu.core.message import Message
    out = []
    for data in frames:
        try:
            if _is_binary(data):
                msg = _message_from_params(Message, decode_tree(data))
            else:
                msg = Message()
                msg.init_from_json_string(
                    data if isinstance(data, str)
                    else bytes(data).decode())
        except DECODE_ERRORS as e:
            out.append(e)
            continue
        out.append(msg)
    return out


__all__ = ["MAGIC", "VERSION", "encode_array", "encode_array_views",
           "decode_array", "encode_tree", "encode_tree_views",
           "decode_tree", "array_wire_nbytes", "tree_wire_nbytes",
           "message_to_wire", "message_to_wire_views",
           "message_from_wire", "message_from_header",
           "parse_wire_header", "peek_wire_envelope", "decode_frames",
           "DECODE_ERRORS"]
