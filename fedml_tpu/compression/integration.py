"""Compressed federated rounds: the engine composition for ``compressor=``.

The plain engine round (``parallel/engine.py make_sim_round``) vmaps
``client_update`` over the cohort and weight-averages the payloads. The
compressed round inserts, per client, the client->server half of the wire:

    delta_k   = local_params_k - global_params
    enc_k     = compress(delta_k + residual_k)        (client-side, EF)
    recon_k   = global_params + decompress(enc_k)     (server-side view)
    residual' = (delta_k + residual_k) - decompress(enc_k)

and then feeds the *reconstructed* states through the usual aggregator
hooks, so FedOpt / robust-FedAvg / FedNova variants compose unchanged --
the server only ever sees what survived compression, exactly as it would
across a real transport. Residuals are carried per client across rounds by
the caller (``FedAvgAPI`` keeps a ``[num_clients, ...]`` stacked pytree and
gathers/scatters the sampled cohort's rows).

Only ``params`` is compressed; batch_stats and other state average at full
fidelity (they are small and bias-sensitive).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from fedml_tpu.core import pytree
from fedml_tpu.compression.codec import tree_wire_nbytes
from fedml_tpu.compression.compressors import Compressor, ErrorFeedback


def _default_payload(local_state, global_state, aux):
    return local_state


def _default_server(global_state, avg_payload, server_state, rng):
    return avg_payload, server_state


def make_compressed_sim_round(spec, cfg, compressor: Compressor,
                              payload_fn=None, server_fn=None):
    """Single-chip compressed round.

    ``fn(global_state, server_state, cohort_data, residuals, rng) ->
    (new_global, new_server_state, new_residuals, info)`` -- the
    ``make_sim_round`` contract plus the cohort's error-feedback residual
    pytree (leading axis = cohort) threaded through.
    """
    from fedml_tpu.parallel.engine import make_client_update

    client_update = make_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    @partial(jax.jit, donate_argnums=(0, 1, 3))
    def round_fn(global_state, server_state, cohort_data, residuals, rng):
        C = cohort_data["mask"].shape[0]
        # rng derivation parity with make_sim_round (folds 1 and 2) so a
        # "none" compressor reproduces the uncompressed trajectory bit-for-
        # bit; fold 3 is the compression stream (stochastic rounding/randk)
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        server_rng = jax.random.fold_in(rng, 2)
        crngs = jax.random.split(jax.random.fold_in(rng, 3), C)
        # named_scope: phase labels in the lowered HLO so jax.profiler
        # traces (and fedtrace's profile_dir runs) segment the round's
        # device time by lifecycle phase -- no host cost, bitwise inert
        with jax.named_scope("local-train"):
            local_states, aux, metrics = jax.vmap(
                client_update, in_axes=(None, 0, 0))(global_state,
                                                     cohort_data, rngs)

        ef = ErrorFeedback(compressor)

        def compress_one(local_state, residual, crng):
            delta = pytree.tree_sub(local_state["params"],
                                    global_state["params"])
            _, dec, new_residual = ef.step(delta, residual,
                                           global_state["params"], crng)
            recon = dict(local_state)
            recon["params"] = pytree.tree_add(global_state["params"], dec)
            return recon, new_residual

        with jax.named_scope("ef-compress"):
            recon_states, new_residuals = jax.vmap(compress_one)(
                local_states, residuals, crngs)
        with jax.named_scope("aggregate"):
            payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
                recon_states, global_state, aux)
            avg_payload = pytree.tree_weighted_mean(payloads, aux["n"])
        with jax.named_scope("server-update"):
            new_global, new_server_state = server_fn(
                global_state, avg_payload, server_state, server_rng)
        return (new_global, new_server_state, new_residuals,
                {"aux": aux, "metrics": metrics})

    return round_fn


class ResidualStore:
    """Per-client error-feedback residuals keyed by STABLE client id.

    EF correctness depends on each client accumulating ITS OWN
    compression error across the rounds it is sampled into (DGC /
    EF-SignSGD semantics). Indexing residuals by *cohort slot* silently
    cross-contaminates clients as soon as two rounds sample different
    cohorts (or a resilience re-attempt reshuffles the reporting subset):
    slot 0's residual would belong to whichever client happened to sit at
    slot 0 last round. This store makes the id-keyed contract explicit
    and testable -- ``gather(ids)`` stacks the cohort's residuals in
    cohort order for the jitted round, ``scatter(ids, updated)`` writes
    each row back to its OWNER id.

    Two backings behind one surface:

    - **dense** (default when ``num_clients`` is known and the stacked
      array fits ``dense_cap_gb``): one device-resident ``[C_total, ...]``
      pytree, rows ARE client ids; gather/scatter are fused ``take`` /
      ``at[].set`` -- the fast path for the cross-silo regime.
    - **sparse** (unbounded populations): a host dict ``id -> numpy
      pytree``, residuals materialize lazily as zeros on first gather --
      memory scales with *touched* clients, never the population, which
      is what lets EF compose with massive cohorts.
    """

    def __init__(self, params_template, num_clients=None, dense_cap_gb=2.0,
                 dense=None):
        import numpy as np

        self._template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            params_template)
        self._bytes_per_client = sum(
            int(np.prod(s.shape, dtype=np.int64)) * s.dtype.itemsize
            for s in jax.tree.leaves(self._template))
        if dense is None:
            dense = (num_clients is not None
                     and num_clients * self._bytes_per_client
                     <= float(dense_cap_gb) * 1e9)
        self.dense = bool(dense)
        if self.dense:
            if num_clients is None:
                raise ValueError("dense ResidualStore needs num_clients")
            self._stacked = jax.tree.map(
                lambda s: jnp.zeros((int(num_clients),) + s.shape, s.dtype),
                self._template)
        else:
            self._rows = {}  # client id -> host numpy pytree

    def gather(self, ids):
        """Stacked residual pytree for ``ids`` (cohort order)."""
        import numpy as np

        if self.dense:
            sel = jnp.asarray(np.asarray(ids, np.int32))
            return jax.tree.map(lambda x: x[sel], self._stacked)
        rows = []
        for i in ids:
            r = self._rows.get(int(i))
            if r is None:
                r = jax.tree.map(
                    lambda s: np.zeros(s.shape, s.dtype), self._template)
            rows.append(r)
        return jax.tree.map(
            lambda *xs: jnp.asarray(np.stack(xs)), *rows)

    def scatter(self, ids, updated):
        """Write each updated row back to its owner id. A duplicate id in
        ``ids`` (cannot happen via ``client_sampling``, which draws
        without replacement) would resolve last-wins."""
        import numpy as np

        if self.dense:
            sel = jnp.asarray(np.asarray(ids, np.int32))
            self._stacked = jax.tree.map(
                lambda full, upd: full.at[sel].set(upd),
                self._stacked, updated)
            return
        host = jax.tree.map(np.asarray, updated)
        for k, i in enumerate(ids):
            self._rows[int(i)] = jax.tree.map(lambda x: x[k].copy(), host)

    def peek(self, client_id):
        """One client's residual as host numpy (zeros if never touched)
        -- the regression tests' observation point."""
        import numpy as np

        if self.dense:
            return jax.tree.map(
                lambda x: np.asarray(x[int(client_id)]), self._stacked)
        r = self._rows.get(int(client_id))
        if r is None:
            return jax.tree.map(
                lambda s: np.zeros(s.shape, s.dtype), self._template)
        return jax.tree.map(lambda x: np.asarray(x), r)


def compressed_payload_nbytes(compressor: Compressor, params_template) -> int:
    """Exact per-client on-wire bytes of one compressed update, computed
    from abstract shapes (``jax.eval_shape`` -- nothing runs on device).
    This is what one client's ``send_model_to_server`` array section costs
    through ``codec.encode_tree``."""
    enc_shapes = jax.eval_shape(
        lambda t: compressor.compress(t, jax.random.PRNGKey(0)),
        params_template)
    return tree_wire_nbytes(enc_shapes)


def raw_payload_nbytes(params_template) -> int:
    """On-wire bytes of the same update uncompressed through the binary
    codec (the ``none`` floor the compression_ratio is measured against)."""
    shapes = jax.eval_shape(lambda t: t, params_template)
    return tree_wire_nbytes(shapes)


__all__ = ["make_compressed_sim_round", "ResidualStore",
           "compressed_payload_nbytes", "raw_payload_nbytes"]
