"""Compressed federated rounds: the engine composition for ``compressor=``.

The plain engine round (``parallel/engine.py make_sim_round``) vmaps
``client_update`` over the cohort and weight-averages the payloads. The
compressed round inserts, per client, the client->server half of the wire:

    delta_k   = local_params_k - global_params
    enc_k     = compress(delta_k + residual_k)        (client-side, EF)
    recon_k   = global_params + decompress(enc_k)     (server-side view)
    residual' = (delta_k + residual_k) - decompress(enc_k)

and then feeds the *reconstructed* states through the usual aggregator
hooks, so FedOpt / robust-FedAvg / FedNova variants compose unchanged --
the server only ever sees what survived compression, exactly as it would
across a real transport. Residuals are carried per client across rounds by
the caller (``FedAvgAPI`` keeps a ``[num_clients, ...]`` stacked pytree and
gathers/scatters the sampled cohort's rows).

Only ``params`` is compressed; batch_stats and other state average at full
fidelity (they are small and bias-sensitive).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from fedml_tpu.core import pytree
from fedml_tpu.compression.codec import tree_wire_nbytes
from fedml_tpu.compression.compressors import Compressor, ErrorFeedback


def _default_payload(local_state, global_state, aux):
    return local_state


def _default_server(global_state, avg_payload, server_state, rng):
    return avg_payload, server_state


def make_compressed_sim_round(spec, cfg, compressor: Compressor,
                              payload_fn=None, server_fn=None):
    """Single-chip compressed round.

    ``fn(global_state, server_state, cohort_data, residuals, rng) ->
    (new_global, new_server_state, new_residuals, info)`` -- the
    ``make_sim_round`` contract plus the cohort's error-feedback residual
    pytree (leading axis = cohort) threaded through.
    """
    from fedml_tpu.parallel.engine import make_client_update

    client_update = make_client_update(spec, cfg)
    payload_fn = payload_fn or _default_payload
    server_fn = server_fn or _default_server

    @partial(jax.jit, donate_argnums=(0, 1, 3))
    def round_fn(global_state, server_state, cohort_data, residuals, rng):
        C = cohort_data["mask"].shape[0]
        # rng derivation parity with make_sim_round (folds 1 and 2) so a
        # "none" compressor reproduces the uncompressed trajectory bit-for-
        # bit; fold 3 is the compression stream (stochastic rounding/randk)
        rngs = jax.random.split(jax.random.fold_in(rng, 1), C)
        server_rng = jax.random.fold_in(rng, 2)
        crngs = jax.random.split(jax.random.fold_in(rng, 3), C)
        # named_scope: phase labels in the lowered HLO so jax.profiler
        # traces (and fedtrace's profile_dir runs) segment the round's
        # device time by lifecycle phase -- no host cost, bitwise inert
        with jax.named_scope("local-train"):
            local_states, aux, metrics = jax.vmap(
                client_update, in_axes=(None, 0, 0))(global_state,
                                                     cohort_data, rngs)

        ef = ErrorFeedback(compressor)

        def compress_one(local_state, residual, crng):
            delta = pytree.tree_sub(local_state["params"],
                                    global_state["params"])
            _, dec, new_residual = ef.step(delta, residual,
                                           global_state["params"], crng)
            recon = dict(local_state)
            recon["params"] = pytree.tree_add(global_state["params"], dec)
            return recon, new_residual

        with jax.named_scope("ef-compress"):
            recon_states, new_residuals = jax.vmap(compress_one)(
                local_states, residuals, crngs)
        with jax.named_scope("aggregate"):
            payloads = jax.vmap(payload_fn, in_axes=(0, None, 0))(
                recon_states, global_state, aux)
            avg_payload = pytree.tree_weighted_mean(payloads, aux["n"])
        with jax.named_scope("server-update"):
            new_global, new_server_state = server_fn(
                global_state, avg_payload, server_state, server_rng)
        return (new_global, new_server_state, new_residuals,
                {"aux": aux, "metrics": metrics})

    return round_fn


def compressed_payload_nbytes(compressor: Compressor, params_template) -> int:
    """Exact per-client on-wire bytes of one compressed update, computed
    from abstract shapes (``jax.eval_shape`` -- nothing runs on device).
    This is what one client's ``send_model_to_server`` array section costs
    through ``codec.encode_tree``."""
    enc_shapes = jax.eval_shape(
        lambda t: compressor.compress(t, jax.random.PRNGKey(0)),
        params_template)
    return tree_wire_nbytes(enc_shapes)


def raw_payload_nbytes(params_template) -> int:
    """On-wire bytes of the same update uncompressed through the binary
    codec (the ``none`` floor the compression_ratio is measured against)."""
    shapes = jax.eval_shape(lambda t: t, params_template)
    return tree_wire_nbytes(shapes)


__all__ = ["make_compressed_sim_round", "compressed_payload_nbytes",
           "raw_payload_nbytes"]
