"""Client-update compressors: jit-compatible pytree transforms.

Each compressor maps a pytree of update deltas to a compact *encoded*
pytree (per-leaf dicts of small arrays) and back. The encoded form is what
rides the wire (``codec.encode_tree`` frames it in binary), and both
directions are pure jax functions, so compress/decompress run inside the
jitted round (single-chip simulation) or on host numpy inputs unchanged
(``jax.tree.map`` + jnp ops accept numpy leaves).

Error feedback (:class:`ErrorFeedback`) carries the per-client compression
residual across rounds -- Deep Gradient Compression (Lin et al. 2018) /
EF-SignSGD (Karimireddy et al. 2019): compress ``delta + residual``, keep
``residual' = (delta + residual) - decompress(encoded)``. Without it the
biased compressors (topk, signsgd) stall FedAvg; with it compressed
convergence tracks uncompressed (see ``tests/test_compression.py``).

Only floating leaves are compressed; integer leaves (step counters, token
tables) pass through exactly under every compressor.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from fedml_tpu.core import pytree as ptu


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _leaf_rngs(rng, tree):
    """One fold-in key per leaf (stable leaf order via tree flattening)."""
    leaves, treedef = jax.tree.flatten(tree)
    keys = [jax.random.fold_in(rng, i) for i in range(len(leaves))]
    return jax.tree.unflatten(treedef, keys)


class Compressor:
    """Protocol: per-leaf ``encode``/``decode`` lifted over pytrees.

    ``compress(tree, rng) -> encoded`` returns a pytree whose leaves are
    dicts of arrays (the wire payload); ``decompress(encoded, template)``
    needs the original ``template`` pytree for shapes/dtypes. Both are
    jit-compatible; every encoded shape is static given the template.
    """

    name = "none"

    def encode(self, x, rng):  # pragma: no cover - interface
        raise NotImplementedError

    def decode(self, enc, shape, dtype):  # pragma: no cover - interface
        raise NotImplementedError

    def compress(self, tree, rng):
        rngs = _leaf_rngs(rng, tree)
        return jax.tree.map(
            lambda x, r: (self.encode(x, r) if _is_float(x)
                          else {"raw": jnp.asarray(x)}),
            tree, rngs)

    def decompress(self, encoded, template):
        # template drives the traversal (its leaves are arrays); encoded is
        # flattened up to template's structure, so each encoded "leaf" is
        # one per-leaf dict of wire arrays
        return jax.tree.map(
            lambda t, enc: (self.decode(enc, t.shape, t.dtype)
                            if _is_float(t) else enc["raw"]),
            template, encoded)

    def __repr__(self):
        return f"{type(self).__name__}()"


class NoneCompressor(Compressor):
    """Identity transform: no information loss; the win over the status quo
    is purely the binary codec (raw bytes vs JSON nested lists)."""

    name = "none"

    def encode(self, x, rng):
        del rng
        return {"values": jnp.asarray(x)}

    def decode(self, enc, shape, dtype):
        return enc["values"].reshape(shape).astype(dtype)


def _k_for(shape, ratio):
    size = int(math.prod(shape)) if shape else 1
    return max(1, int(math.ceil(ratio * size)))


class TopKCompressor(Compressor):
    """Per-leaf magnitude top-k sparsification (DGC-style): keep the k
    largest-|x| entries of each flattened leaf as (values, int32 indices)."""

    name = "topk"

    def __init__(self, ratio=0.01):
        if not 0 < ratio <= 1:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        self.ratio = float(ratio)

    def encode(self, x, rng):
        del rng
        x = jnp.asarray(x)
        flat = x.reshape(-1)
        k = _k_for(x.shape, self.ratio)
        _, idx = jax.lax.top_k(jnp.abs(flat.astype(jnp.float32)), k)
        idx = idx.astype(jnp.int32)
        return {"values": flat[idx], "indices": idx}

    def decode(self, enc, shape, dtype):
        size = int(math.prod(shape)) if shape else 1
        flat = jnp.zeros((size,), dtype).at[enc["indices"]].set(
            enc["values"].astype(dtype))
        return flat.reshape(shape)

    def __repr__(self):
        return f"TopKCompressor(ratio={self.ratio})"


class RandKCompressor(TopKCompressor):
    """Uniform-random k sparsification, rescaled by 1/ratio so the encoded
    update is an unbiased estimator of the input (Stich et al. 2018)."""

    name = "randk"

    def encode(self, x, rng):
        x = jnp.asarray(x)
        flat = x.reshape(-1)
        k = _k_for(x.shape, self.ratio)
        idx = jax.random.permutation(rng, flat.shape[0])[:k].astype(jnp.int32)
        scale = flat.shape[0] / k
        return {"values": flat[idx] * jnp.asarray(scale, flat.dtype),
                "indices": idx}

    def __repr__(self):
        return f"RandKCompressor(ratio={self.ratio})"


class QSGDCompressor(Compressor):
    """Stochastic uniform quantization to signed int8 with a per-leaf fp32
    scale (QSGD, Alistarh et al. 2017). ``bits`` in [2, 8] sets the level
    count (2^(bits-1) - 1 magnitude levels); storage is int8 either way, so
    the wire cost is 1 byte/element + 4 bytes/leaf -- bits < 8 trades
    accuracy for nothing on this codec and exists for fidelity sweeps.
    Stochastic rounding keeps the quantizer unbiased given the scale."""

    name = "qsgd"

    def __init__(self, bits=8):
        if not 2 <= int(bits) <= 8:
            raise ValueError(f"qsgd bits must be in [2, 8], got {bits}")
        self.bits = int(bits)
        self.levels = 2 ** (self.bits - 1) - 1

    def encode(self, x, rng):
        xf = jnp.asarray(x).astype(jnp.float32)
        scale = jnp.max(jnp.abs(xf))
        safe = jnp.maximum(scale, 1e-30)
        y = xf / safe * self.levels
        noise = jax.random.uniform(rng, xf.shape)
        q = jnp.clip(jnp.floor(y + noise), -self.levels, self.levels)
        return {"q": q.astype(jnp.int8),
                "scale": scale.astype(jnp.float32)}

    def decode(self, enc, shape, dtype):
        y = (enc["q"].astype(jnp.float32)
             * enc["scale"] / self.levels)
        return y.reshape(shape).astype(dtype)

    def __repr__(self):
        return f"QSGDCompressor(bits={self.bits})"


class SignSGDCompressor(Compressor):
    """1-bit sign compression with a per-leaf mean-|x| magnitude (scaled
    SignSGD). Signs are a bool array -- the wire codec bit-packs bools, so
    the on-wire cost is 1 bit/element + 4 bytes/leaf (~32x vs fp32)."""

    name = "signsgd"

    def encode(self, x, rng):
        del rng
        xf = jnp.asarray(x).astype(jnp.float32)
        return {"sign": xf >= 0,
                "scale": jnp.mean(jnp.abs(xf)).astype(jnp.float32)}

    def decode(self, enc, shape, dtype):
        mag = jnp.where(enc["sign"], enc["scale"], -enc["scale"])
        return mag.reshape(shape).astype(dtype)


class ErrorFeedback:
    """Residual-carrying wrapper: the client-side accumulator that makes
    biased compressors converge. Stateless module; the residual pytree is
    carried by the caller (per client, across rounds)."""

    def __init__(self, compressor: Compressor):
        self.compressor = compressor

    def init(self, template):
        return ptu.tree_zeros_like(template)

    def step(self, delta, residual, template, rng):
        """Compress ``delta + residual``; returns ``(encoded, decoded,
        new_residual)`` where ``decoded`` is what the server reconstructs."""
        comp_in = ptu.tree_add(delta, residual)
        encoded = self.compressor.compress(comp_in, rng)
        decoded = self.compressor.decompress(encoded, template)
        new_residual = ptu.tree_sub(comp_in, decoded)
        return encoded, decoded, new_residual


_REGISTRY = {
    "none": NoneCompressor,
    "topk": TopKCompressor,
    "randk": RandKCompressor,
    "qsgd": QSGDCompressor,
    "signsgd": SignSGDCompressor,
}


def get_compressor(spec):
    """Spec string -> compressor instance (``None``/empty -> ``None``).

    Grammar: ``name[:arg]`` -- ``none``, ``topk:0.01``, ``randk:0.1``,
    ``qsgd:8``, ``signsgd``. An already-constructed :class:`Compressor`
    passes through, so APIs accept either form.
    """
    if spec is None or isinstance(spec, Compressor):
        return spec
    s = str(spec).strip().lower()
    if not s or s in ("0", "off", "false"):
        return None
    name, _, arg = s.partition(":")
    if name not in _REGISTRY:
        raise ValueError(f"unknown compressor {name!r} "
                         f"(known: {sorted(_REGISTRY)})")
    cls = _REGISTRY[name]
    if not arg:
        return cls()
    if name in ("topk", "randk"):
        return cls(ratio=float(arg))
    if name == "qsgd":
        return cls(bits=int(arg))
    raise ValueError(f"compressor {name!r} takes no argument (got {arg!r})")


__all__ = ["Compressor", "NoneCompressor", "TopKCompressor",
           "RandKCompressor", "QSGDCompressor", "SignSGDCompressor",
           "ErrorFeedback", "get_compressor"]
