"""Tree orchestrator: spawn, supervise, and tear down the process tree.

:func:`run_tree` turns a :class:`~fedml_tpu.topology.tree.TreeSpec`
into a running federation: one edge process per edge slot
(:mod:`fedml_tpu.topology.edge`), one sharded soak swarm per bottom
edge (:mod:`fedml_tpu.net.soak` ``--gid_base/--gid_stride``: LOCAL
ranks on the wire, GLOBAL ids in the oracle), and the REAL
coordinator -- an
:class:`~fedml_tpu.resilience.async_agg.AsyncBufferedFedAvgServer`
over the spec's transport -- in THIS process, the same way
``net/soak.py`` runs its parent half.

Supervision: while the coordinator runs, a dead edge process (crash or
kill) is respawned with its exact original argv; the fresh process
re-dials its parent, whose transport accepts the late HELLO as a
rejoin (PEER_JOIN) and the coordinator resumes it mid-round -- no
orchestrator-side protocol beyond "start the same process again", by
design: the rejoin path IS the recovery protocol. The dead edge's
swarm shards died with their sockets, so the subtree's swarms respawn
with it.

Teardown is the stop wave, not signals: the coordinator finishing its
updates sends ``__stop__`` to the tier-1 edges, each edge's shutdown
forwards it down its own star, the swarms close on it, and every
process exits by itself; the orchestrator then reaps with a timeout
and force-kills only what overstayed (reported in the result -- a
clean run kills nothing and leaves no zombies, pinned in
tests/test_topology.py).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np

from fedml_tpu.observability import enable
from fedml_tpu.observability.perfmon import append_ledger
from fedml_tpu.topology.tree import TreeSpec


def _free_port(host):
    s = socket.socket()
    s.bind((host, 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Child:
    """One supervised subprocess: its argv (for respawn) + handle."""

    def __init__(self, name, cmd, parse_stdout=True):
        self.name = name
        self.cmd = cmd
        self.parse_stdout = parse_stdout
        self.proc = None
        self.respawns = 0
        self.summaries = []

    def spawn(self):
        self.proc = subprocess.Popen(
            self.cmd, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        return self.proc

    def collect(self, timeout=30.0):
        """Reap; parse the last JSON stdout line as the summary."""
        if self.proc is None:
            return None
        try:
            out, _ = self.proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None
        for line in (out or "").strip().splitlines():
            try:
                self.summaries.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return self.summaries[-1] if self.summaries else None


def plan_tree(spec: TreeSpec, spec_path, status_dir, ledger_path=None):
    """The spawn plan: ``(coord_port, edges, swarms)`` with one
    :class:`_Child` per edge slot and per bottom-edge swarm shard.
    Ports are allocated here, once -- a respawned child reuses its
    port so its parent's rejoin admits the same topology slot."""
    host = spec.host
    coord_port = spec.coord_port or _free_port(host)
    ports = {path: _free_port(host) for path in spec.edge_paths()}
    edges, swarms = [], []
    for path in spec.edge_paths():
        tier = len(path)
        up_port = coord_port if tier == 1 else ports[path[:-1]]
        up_world = spec.fanout[tier - 1] + 1
        world = (spec.fanout[tier] + 1 if tier < spec.tiers
                 else spec.leaves_per_edge + 1)
        name = f"tier{tier}-edge{'.'.join(str(e) for e in path)}"
        cmd = [sys.executable, "-m", "fedml_tpu.topology.edge",
               "--spec", str(spec_path), "--tier", str(tier),
               "--edge-rank", str(path[-1] + 1),
               "--upstream-port", str(up_port),
               "--upstream-world", str(up_world),
               "--listen-port", str(ports[path]),
               "--world", str(world),
               "--status", os.path.join(status_dir,
                                        f"{name}.status.json")]
        if ledger_path:
            cmd += ["--ledger", str(ledger_path)]
        edges.append(_Child(name, cmd))
        if tier == spec.tiers:  # bottom edge: its leaf swarm shard
            gid_base, gid_stride = spec.leaf_slice(path)
            scmd = [sys.executable, "-m", "fedml_tpu.net.soak",
                    "--swarm", "--host", host,
                    "--port", str(ports[path]),
                    "--clients", str(spec.leaves_per_edge),
                    "--world", str(spec.leaves_per_edge + 1),
                    "--jitter_s", str(spec.jitter_s),
                    "--seed", str(spec.seed),
                    "--gid_base", str(gid_base),
                    "--gid_stride", str(gid_stride)]
            if spec.trace:
                scmd += ["--trace", str(spec.trace)]
            swarms.append(_Child(f"swarm-{name}", scmd))
    return coord_port, edges, swarms


def run_tree(spec: TreeSpec, workdir, init_params=None, supervise=True,
             join_timeout=600.0, metrics_logger=None,
             ledger_path=None, on_spawned=None):
    """Run the spec's tree to completion. ``workdir`` receives the
    spec file and every tier's status.json; ``ledger_path`` (optional)
    collects the per-tier reports/sec rows plus the coordinator's.
    ``on_spawned(children)`` is a test hook called once every process
    is up (the edge-kill test reaches through it). Returns a result
    dict: the coordinator server, per-process summaries and statuses,
    and the supervision/teardown counters -- ``zombies`` MUST be 0 on
    a clean run."""
    from fedml_tpu.resilience.async_agg import AsyncBufferedFedAvgServer
    from fedml_tpu.resilience.steering import PaceController
    from fedml_tpu.topology.edge import _make_comm

    os.makedirs(workdir, exist_ok=True)
    spec_path = spec.to_file(os.path.join(workdir, "tree.json"))
    coord_port, edges, swarms = plan_tree(spec, spec_path, workdir,
                                          ledger_path=ledger_path)
    program = spec.round_program()
    policy = program.aggregation
    if init_params is None:
        init_params = {"w": np.zeros(8, np.float32),
                       "b": np.ones(4, np.float32)}
    pace = None
    if spec.steering:
        pace = PaceController(bounds=spec.pace_bounds(0), seed=spec.seed,
                              buffer_k=policy.buffer_k,
                              flush_deadline_s=policy.flush_deadline_s)
    children = edges + swarms
    # children dial with retry: spawn everything, then bring the
    # coordinator up under the burst (run_soak's discipline)
    for c in children:
        c.spawn()
    respawned = killed = 0
    world = spec.fanout[0] + 1
    t0 = time.monotonic()
    status_path = os.path.join(workdir, "tier0-coordinator.status.json")
    try:
        with enable(perfmon=True, status_path=status_path,
                    metrics_logger=metrics_logger):
            comm = _make_comm(spec.transport, spec.host, coord_port, 0,
                              world,
                              timeout=max(120.0, spec.n_leaves / 50.0))
            server = AsyncBufferedFedAvgServer(
                None, comm, world, init_params, spec.total_updates,
                policy, metrics_logger=metrics_logger,
                pace_controller=pace)
            # the coordinator executes the tree's ONE program: its
            # status.json must carry the same manifest as every tier's
            server.program = program
            server._host = program.host_view()
            server.agg = server._host.make_aggregator()
            server.register_message_receive_handlers()
            server.start()
            if on_spawned is not None:
                on_spawned({c.name: c for c in children})
            loop = threading.Thread(target=comm.handle_receive_message,
                                    daemon=True, name="tree-coordinator")
            loop.start()
            deadline = time.monotonic() + join_timeout
            while loop.is_alive() and time.monotonic() < deadline:
                loop.join(timeout=0.5)
                if not supervise or not loop.is_alive():
                    continue
                for c in children:
                    if c.proc.poll() is None:
                        continue
                    # a dead process while the run is live: respawn its
                    # exact argv -- the fresh HELLO is a transport
                    # rejoin, and the mid-round resume does the rest
                    c.collect(timeout=5.0)
                    logging.warning("tree: %s died (rc=%s) -- respawning",
                                    c.name, c.proc.returncode)
                    c.respawns += 1
                    respawned += 1
                    c.spawn()
            if loop.is_alive():
                comm.stop_receive_message()
                loop.join(timeout=15.0)
                raise TimeoutError(
                    f"tree coordinator hung past {join_timeout}s "
                    f"(update {server.agg.version}/{spec.total_updates},"
                    f" failed={server.failed})")
    finally:
        # the stop wave should have cascaded; reap, then force-kill
        # only what overstayed. A swarm whose edge CRASHED (nonzero
        # exit, not respawned) can never hear the wave -- its dial
        # retries would stall the whole reap budget, so orphans get a
        # short grace and a terminate instead
        edge_by_name = {c.name: c for c in edges}
        for s in swarms:
            e = edge_by_name.get(s.name[len("swarm-"):])
            if (s.proc is not None and s.proc.poll() is None
                    and e is not None and e.proc is not None
                    and e.proc.poll() not in (None, 0)):
                try:
                    s.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    s.proc.terminate()
                    killed += 1
        reap_by = time.monotonic() + 60.0
        for c in children:
            if c.proc is None:
                continue
            try:
                c.proc.wait(timeout=max(0.1, reap_by - time.monotonic()))
            except subprocess.TimeoutExpired:
                c.proc.kill()
                killed += 1
            c.collect(timeout=10.0)
    zombies = sum(1 for c in children if c.proc.poll() is None)
    wall = time.monotonic() - t0
    statuses = {}
    for f in sorted(os.listdir(workdir)):
        if f.endswith(".status.json"):
            with open(os.path.join(workdir, f)) as fh:
                statuses[f] = json.load(fh)
    total_reports = sum(s.get("reports", 0) for c in swarms
                       for s in c.summaries)
    if ledger_path:
        append_ledger({
            "bench": "tree-soak",
            "metric": (f"tree-soak leaf reports/sec ({spec.n_leaves} "
                       f"leaves, fanout {'x'.join(map(str, spec.fanout))}"
                       f", {spec.transport}, "
                       f"{spec.compressor or 'plain'} upstream, "
                       f"{'diurnal' if spec.trace else 'uniform'} "
                       f"arrivals, "
                       f"{'steered' if spec.steering else 'fixed'})"),
            "value": round(total_reports / max(wall, 1e-9), 2),
            "unit": "reports/sec",
            "leaves": spec.n_leaves, "updates": server.agg.version,
            "respawned": respawned, "killed": killed,
            "wall_s": round(wall, 3)}, ledger_path)
    return {"server": server, "history": server.history,
            "statuses": statuses,
            "edge_summaries": {c.name: c.summaries for c in edges},
            "swarm_summaries": {c.name: c.summaries for c in swarms},
            "respawned": respawned, "killed": killed,
            "zombies": zombies, "wall_s": round(wall, 3)}


__all__ = ["plan_tree", "run_tree"]
