"""Multi-process federation trees (Bonawitz MLSys'19 actor hierarchy).

``net/fanin.py`` proved the tiers compose in one process; this package
makes them real processes: :mod:`.tree` declares the shape
(:class:`~fedml_tpu.topology.tree.TreeSpec`), :mod:`.edge` is the edge
process entrypoint (one :class:`~fedml_tpu.net.fanin.EdgeAggregator`
per process: leaf-star server below, compressed-wire client above),
and :mod:`.orchestrator` spawns, supervises, and tears down the tree
(:func:`~fedml_tpu.topology.orchestrator.run_tree`).
"""

from fedml_tpu.topology.tree import TreeSpec, manifest_core
from fedml_tpu.topology.orchestrator import run_tree

__all__ = ["TreeSpec", "manifest_core", "run_tree"]
