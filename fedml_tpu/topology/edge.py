"""The edge process: one fan-in tier member as a real OS process.

``python -m fedml_tpu.topology.edge --spec tree.json --tier T ...``
runs ONE :class:`~fedml_tpu.net.fanin.EdgeAggregator` for its slot in
the tree: a leaf-star server downstream (its children are swarm leaves
or deeper edge processes), a dialing client upstream (the coordinator
or its parent edge), both over the spec's transport. The aggregator
drives the tree's ONE shared :class:`~fedml_tpu.program.RoundProgram`
via ``host_view()`` -- the same fold every other tier executes -- and,
when the spec arms steering, its own per-tier
:class:`~fedml_tpu.resilience.steering.PaceController` whose bounds
are the spec's tier bounds intersected with the coordinator's
(:meth:`TreeSpec.pace_bounds`).

Per-tier observability: the process arms its own
``observability.enable(perfmon=True, status_path=...)`` scope, so its
``status.json`` (program manifest + tier id + rounds/hour, sorted
keys) and its registry histograms are THIS tier's, not a mashup --
which is precisely what makes per-tier steering read per-tier
evidence. On exit it appends a per-tier reports/sec row to the ledger
(``--ledger``) and prints a one-line JSON summary to stdout for the
orchestrator to collect.

Lifecycle: construct the uplink first (dials with retry until the
parent listens), then the downlink (its constructor waits for every
child HELLO), then serve until the upstream STOP wave or parent loss
tears the subtree down (``EdgeAggregator.run`` cascades the stop to
the children).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time

from fedml_tpu.net.fanin import EdgeAggregator
from fedml_tpu.observability import enable
from fedml_tpu.observability.perfmon import append_ledger
from fedml_tpu.resilience.steering import PaceController
from fedml_tpu.topology.tree import TreeSpec


def _make_comm(transport, host, port, rank, world, timeout):
    # inline per-transport construction (fedcheck FL126 types the
    # com_manager from these sites, same shape as fanin.run_fanin_fedavg)
    if transport == "eventloop":
        from fedml_tpu.net.eventloop import EventLoopCommManager
        return EventLoopCommManager(host, port, rank, world,
                                    timeout=timeout)
    from fedml_tpu.core.comm.tcp import TcpCommManager
    return TcpCommManager(host, port, rank, world, timeout=timeout)


def run_edge_process(spec: TreeSpec, tier: int, edge_rank: int,
                     upstream_host: str, upstream_port: int,
                     upstream_world: int, listen_port: int, world: int,
                     status_path=None, ledger_path=None,
                     timeout: float = 120.0) -> dict:
    """Run one edge slot to completion; returns its summary dict."""
    program = spec.round_program()
    round_policy = program.cohort
    pace = None
    if spec.steering:
        # per-tier controller: starts from the program's knobs, bounded
        # by the tier envelope (intersected with the coordinator's)
        pace = PaceController(
            bounds=spec.pace_bounds(tier), seed=spec.seed,
            deadline_s=round_policy.deadline_s or 1.0,
            overselect=round_policy.overselect)
    up = _make_comm(spec.transport, upstream_host, upstream_port,
                    edge_rank, upstream_world, timeout)
    down = _make_comm(spec.transport, spec.host, listen_port, 0, world,
                      timeout)
    # only the coordinator-facing hop ships the compressed wire: inner
    # hops move pre-aggregated folds between co-located processes
    compressor = spec.compressor if tier == 1 else None
    edge = EdgeAggregator(edge_rank, up, upstream_world, down, world,
                          round_policy=round_policy,
                          compressor=compressor, pace_controller=pace,
                          tier=tier, program=program)
    t0 = time.monotonic()
    with enable(perfmon=True, status_path=status_path):
        edge._report_health()  # tier identity visible before round 1
        edge.run()
        wall = time.monotonic() - t0
        summary = edge.status_fields()
    summary["wall_s"] = round(wall, 3)
    if ledger_path:
        append_ledger({
            "bench": "tree-edge",
            "metric": (f"tree-edge reports/sec (tier {tier}, "
                       f"{spec.transport}, "
                       f"{spec.compressor or 'plain'} upstream)"),
            "value": round(edge.leaf_reports / max(wall, 1e-9), 2),
            "unit": "reports/sec",
            "tier": tier, "edge_rank": edge_rank,
            "reports": edge.leaf_reports,
            "rounds_forwarded": edge.rounds_forwarded,
            "wall_s": round(wall, 3)}, ledger_path)
    return summary


def _main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--spec", required=True,
                   help="TreeSpec JSON file (topology.tree)")
    p.add_argument("--tier", type=int, required=True,
                   help="this edge's tier (1 = under the coordinator)")
    p.add_argument("--edge-rank", type=int, required=True,
                   help="upstream dial rank (1..fanout of the parent)")
    p.add_argument("--upstream-host", default=None)
    p.add_argument("--upstream-port", type=int, required=True)
    p.add_argument("--upstream-world", type=int, required=True)
    p.add_argument("--listen-port", type=int, required=True)
    p.add_argument("--world", type=int, required=True,
                   help="downlink world size (children + 1)")
    p.add_argument("--status", default=None,
                   help="this tier member's status.json path")
    p.add_argument("--ledger", default=None,
                   help="JSONL perf ledger for the per-tier "
                        "reports/sec row")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--log-level", default="WARNING")
    args = p.parse_args(argv)
    logging.basicConfig(level=getattr(logging, args.log_level.upper(),
                                      logging.WARNING))
    spec = TreeSpec.from_file(args.spec)
    summary = run_edge_process(
        spec, args.tier, args.edge_rank,
        args.upstream_host or spec.host, args.upstream_port,
        args.upstream_world, args.listen_port, args.world,
        status_path=args.status, ledger_path=args.ledger,
        timeout=args.timeout)
    sys.stdout.write(json.dumps(summary, sort_keys=True) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(_main())


__all__ = ["run_edge_process"]
