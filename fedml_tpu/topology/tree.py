"""TreeSpec: the declarative shape of a multi-process federation tree.

One JSON document describes the whole tree -- fan-out per edge tier,
leaves per bottom edge, the transports, the upstream wire codec, the
shared :class:`~fedml_tpu.program.RoundProgram` manifest, the diurnal
trace, and the steering bounds -- so the orchestrator, every edge
process, and the CI gate all read the SAME spec instead of re-deriving
the shape from flag soup. Serialization is ``sort_keys`` JSON (the
FL135 discipline: specs diff cleanly and hash stably).

Leaf identity is arithmetic, not enumerated: the tree partitions the
flat leaf population ``1..N`` with the nested
:func:`~fedml_tpu.net.fanin.round_robin_groups` rule (the same slices
the simulation path's group axis trains), and a nested round-robin
slice is an arithmetic progression -- so a bottom edge's whole leaf
set is two integers, ``(gid_base, gid_stride)``
(:meth:`TreeSpec.leaf_slice`), which is exactly what a sharded soak
swarm needs to key its oracle by GLOBAL id while dialing LOCAL ranks.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class TreeSpec:
    """The federation tree, declaratively.

    Attributes:
      fanout: edge fan-out per tier, root-first -- ``(2,)`` is a
        two-tier tree with 2 edges under the coordinator; ``(2, 2)``
        adds edges-of-edges (4 bottom edges in 2 groups of 2).
      leaves_per_edge: swarm leaves under each bottom edge.
      total_updates: coordinator updates before the tree tears down.
      transport: ``"eventloop"`` (the scalable default) or ``"tcp"``,
        for every star in the tree.
      compressor: upstream wire codec spec (``"qsgd"``/``"topk:0.01"``)
        on the coordinator-facing edge hop; None/"none" = plain.
      program: RoundProgram manifest dict shared by every tier's
        status.json (None = the default program's manifest).
      trace: DiurnalTrace JSON path the leaf swarms replay (None =
        uniform ``jitter_s``).
      jitter_s / seed: the pre-trace reply model + the tree-wide seed.
      buffer_k / flush_deadline_s / staleness_decay: coordinator
        aggregation knobs (buffer_k None = one slot per tier-1 edge).
      edge_deadline_s / edge_quorum: every edge's round policy
        (deadline 0 = wait for all alive leaves; the soak wants a real
        deadline so phase-dark leaves cannot wedge an edge; quorum 0
        completes any deadline round with >= 1 report, degraded).
      steering: arm one PaceController per tier (coordinator + every
        edge); per-tier bounds are ``tier_bounds`` INTERSECTED with
        ``bounds`` (PaceBounds.intersect -- an edge can never steer
        outside the coordinator's envelope).
      bounds / tier_bounds: ``{knob: [lo, hi]}`` PaceBounds overrides
        for the coordinator / the edge tiers.
      host / coord_port: where the coordinator listens (port None =
        orchestrator picks a free one).
    """

    fanout: tuple = (2,)
    leaves_per_edge: int = 4
    total_updates: int = 3
    transport: str = "eventloop"
    compressor: Optional[str] = None
    program: Optional[dict] = None
    trace: Optional[str] = None
    jitter_s: float = 0.0
    seed: int = 0
    buffer_k: Optional[int] = None
    flush_deadline_s: float = 30.0
    staleness_decay: float = 0.0
    edge_deadline_s: float = 0.0
    edge_quorum: float = 0.0
    steering: bool = False
    bounds: dict = field(default_factory=dict)
    tier_bounds: dict = field(default_factory=dict)
    host: str = "localhost"
    coord_port: Optional[int] = None

    def __post_init__(self):
        object.__setattr__(self, "fanout",
                           tuple(int(f) for f in self.fanout))
        if not self.fanout or any(f < 1 for f in self.fanout):
            raise ValueError(f"fanout {self.fanout!r}: need >=1 edge "
                             "per tier")
        if int(self.leaves_per_edge) < 1:
            raise ValueError("leaves_per_edge must be >= 1")

    # -- shape arithmetic ---------------------------------------------------
    @property
    def tiers(self) -> int:
        """Edge tiers (coordinator and leaves not counted)."""
        return len(self.fanout)

    @property
    def n_bottom_edges(self) -> int:
        n = 1
        for f in self.fanout:
            n *= f
        return n

    @property
    def n_leaves(self) -> int:
        return self.n_bottom_edges * int(self.leaves_per_edge)

    def edge_paths(self):
        """Every edge address as a path tuple, tier by tier:
        ``(e1,)`` tier-1 edges, ``(e1, e2)`` their children, ... --
        0-based indices into each tier's fan-out."""
        for depth in range(1, self.tiers + 1):
            for path in itertools.product(
                    *(range(f) for f in self.fanout[:depth])):
                yield path

    def leaf_slice(self, path) -> tuple:
        """``(gid_base, gid_stride)`` of the BOTTOM edge at ``path``:
        the arithmetic progression nested ``round_robin_groups`` hands
        it over the flat population ``1..n_leaves`` (``ids[e::F]`` of
        an arithmetic slice is an arithmetic slice; induction over
        tiers). Its leaves are ``gid_base + i * gid_stride`` for
        ``i in range(leaves_per_edge)``."""
        path = tuple(int(e) for e in path)
        if len(path) != self.tiers:
            raise ValueError(f"path {path!r}: bottom edges live at "
                             f"depth {self.tiers}")
        base, stride = 1, 1
        for e, f in zip(path, self.fanout):
            if not 0 <= e < f:
                raise ValueError(f"path {path!r} outside fanout "
                                 f"{self.fanout!r}")
            base += e * stride
            stride *= f
        return base, stride

    # -- the one program ----------------------------------------------------
    def round_program(self):
        """The ONE :class:`~fedml_tpu.program.RoundProgram` every tier
        of this tree executes: ``program`` manifest when given, else
        derived from the spec knobs (cohort leg = the edge round
        policy, aggregation leg = the coordinator's buffer knobs,
        codec leg = the upstream wire). Every tier's status.json
        carries this manifest; per-tier steering then evolves the
        steered knobs (cohort.deadline_s/overselect at the edges,
        aggregation buffer/flush at the root) while the core --
        quorum, retries, decay, codec -- stays invariant
        (:func:`manifest_core`)."""
        from fedml_tpu.program import AggregationPolicy, RoundProgram
        from fedml_tpu.program.cohort import CohortPolicy
        if self.program is not None:
            return RoundProgram.from_manifest(self.program)
        return RoundProgram(
            cohort=CohortPolicy(deadline_s=float(self.edge_deadline_s),
                                quorum=float(self.edge_quorum)),
            aggregation=AggregationPolicy(
                buffer_k=(int(self.buffer_k) if self.buffer_k is not None
                          else self.fanout[0]),
                staleness_decay=float(self.staleness_decay),
                flush_deadline_s=float(self.flush_deadline_s)),
            codec=self.compressor or "none")

    def pace_bounds(self, tier: int = 0):
        """The PaceBounds a tier's controller is constructed with:
        tier 0 (the coordinator) gets ``bounds``; every edge tier gets
        ``tier_bounds`` INTERSECTED with the coordinator's
        (:meth:`~fedml_tpu.resilience.steering.PaceBounds.intersect`)
        -- the per-tier clamp that keeps a tier inside the root's
        steering envelope."""
        from fedml_tpu.resilience.steering import PaceBounds

        def build(over):
            kw = {k: tuple(v) for k, v in (over or {}).items()}
            return PaceBounds(**kw)

        outer = build(self.bounds)
        if tier == 0:
            return outer
        return build(self.tier_bounds).intersect(outer)

    # -- serialization ------------------------------------------------------
    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["fanout"] = list(self.fanout)
        return json.dumps(d, sort_keys=True, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "TreeSpec":
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"TreeSpec: unknown keys {sorted(unknown)}")
        return cls(**data)

    def to_file(self, path) -> str:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")
        return str(path)

    @classmethod
    def from_file(cls, path) -> "TreeSpec":
        with open(path) as f:
            return cls.from_json(f.read())


#: RoundProgram manifest knobs per-tier pace steering may legitimately
#: evolve mid-run; everything else must match across every tier of one
#: tree (the CI gate compares manifest_core of every status.json).
_STEERED_KNOBS = {"cohort": ("deadline_s", "overselect"),
                  "aggregation": ("buffer_k", "flush_deadline_s")}


def manifest_core(manifest: dict) -> dict:
    """A RoundProgram manifest with the steered knobs normalized out:
    the per-tier INVARIANT identity of the program (codec, quorum,
    retries, staleness law). Two tiers of one tree must agree on the
    core even while their controllers steer the knobs apart."""
    core = json.loads(json.dumps(manifest, sort_keys=True))
    for leg, knobs in _STEERED_KNOBS.items():
        for k in knobs:
            core.get(leg, {}).pop(k, None)
    return core


__all__ = ["TreeSpec", "manifest_core"]

